"""End-to-end integrity: checksummed `.m` artifacts, hostile-header
rejection, the numeric-health watchdog, and poisoned-slot quarantine.

The corrupt-file corpus pins the open-time contract — a truncated or
bit-flipped file is REJECTED with the first bad tensor's name and byte
offset, never silently loaded — and the watchdog tests pin the serving
contract: a decode row whose logits go non-finite finishes with
``finish_reason "error"`` while every sibling row stays bit-identical to a
clean run.
"""

import json
import struct
import threading
import zlib
from argparse import Namespace

import numpy as np
import pytest

from dllama_tpu import faults
from dllama_tpu.formats.spec import FormatError, parse_header, write_header
from dllama_tpu.formats.weights import (
    ChecksumError,
    ModelWriter,
    WeightFileReader,
    tensor_plan,
    write_model,
)
from dllama_tpu.quants import blocks
from tests.test_formats import random_tensors, tiny_spec


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    """A failing fault test must not poison later tests in the process."""
    yield
    faults.clear()


def _write(tmp_path, wft=blocks.Q40, checksums=None, name="m.m", seed=0):
    spec = tiny_spec(wft=wft)
    tensors = random_tensors(spec, seed=seed)
    path = str(tmp_path / name)
    with ModelWriter(path, spec, checksums=checksums) as w:
        for e in w.plan:
            w.write_next(e.name, tensors[e.name])
    return path, spec, tensors


# ---------------------------------------------------------------------------
# The integrity section: write, verify, reference-loadability
# ---------------------------------------------------------------------------

def test_checksummed_file_roundtrips_and_verifies(tmp_path):
    path, spec, tensors = _write(tmp_path)
    with WeightFileReader(path) as r:
        assert r.has_integrity
        report = r.verify()
        assert report["ok"] and not report["failures"]
        assert report["tensors"] == len(r.entries)
        # normal reads still work (and are CRC-checked on first touch)
        got = r.read_tensor("token_embedding")
        np.testing.assert_array_equal(
            got.reshape(-1), tensors["token_embedding"])


def test_section_is_pure_suffix_reference_layout_unchanged(tmp_path):
    """The checksummed file is the legacy file plus trailing bytes — the
    reference loader reads tensors sequentially by offset and never checks
    the file size, so checksummed artifacts stay loadable there."""
    with_path, _, _ = _write(tmp_path, name="with.m", checksums=True)
    without_path, _, _ = _write(tmp_path, name="without.m", checksums=False)
    with_bytes = open(with_path, "rb").read()
    without_bytes = open(without_path, "rb").read()
    assert with_bytes[: len(without_bytes)] == without_bytes
    assert len(with_bytes) > len(without_bytes)
    assert with_bytes[len(without_bytes):][:4] == b"DLCK"


def test_legacy_file_without_section_still_loads(tmp_path):
    path, _, tensors = _write(tmp_path, checksums=False)
    with WeightFileReader(path) as r:
        assert not r.has_integrity
        report = r.verify()
        assert report["ok"] and not report["has_integrity"]
        got = r.read_tensor("rms_final")
        np.testing.assert_array_equal(got, tensors["rms_final"])


def test_write_model_defaults_to_checksums(tmp_path):
    spec = tiny_spec(wft=blocks.F32)
    path = str(tmp_path / "d.m")
    write_model(path, spec, random_tensors(spec))
    with WeightFileReader(path) as r:
        assert r.has_integrity


# ---------------------------------------------------------------------------
# Corrupt-file corpus: every rejection names what is wrong
# ---------------------------------------------------------------------------

def test_truncated_mid_tensor_names_first_cut_tensor(tmp_path):
    path, spec, _ = _write(tmp_path)
    with WeightFileReader(path) as r:
        # cut mid-way through the SECOND tensor: the error must name it (not
        # the last one) with its byte span
        bad = r.entries[1]
    with open(path, "r+b") as f:
        f.truncate(bad.offset + bad.nbytes // 2)
    with pytest.raises(FormatError) as ei:
        WeightFileReader(path)
    msg = str(ei.value)
    assert bad.name in msg and str(bad.offset) in msg and "truncated" in msg


def test_truncation_inside_integrity_section_rejected(tmp_path):
    path, _, _ = _write(tmp_path)
    size = __import__("os").path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 1)
    with pytest.raises(FormatError, match="integrity section"):
        WeightFileReader(path)


def test_trailing_garbage_rejected(tmp_path):
    path, _, _ = _write(tmp_path, checksums=False)
    with open(path, "ab") as f:
        f.write(b"\x00" * 32)
    with pytest.raises(FormatError, match="integrity section"):
        WeightFileReader(path)


def test_section_self_checksum_detects_section_corruption(tmp_path):
    path, _, _ = _write(tmp_path)
    size = __import__("os").path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - 10)  # inside the CRC table
        b = f.read(1)
        f.seek(size - 10)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(FormatError, match="its own checksum"):
        WeightFileReader(path)


def _flip_byte(path, file_offset):
    with open(path, "r+b") as f:
        f.seek(file_offset)
        b = f.read(1)
        f.seek(file_offset)
        f.write(bytes([b[0] ^ 0x01]))


def test_bitflip_caught_on_first_read(tmp_path):
    path, _, _ = _write(tmp_path)
    with WeightFileReader(path) as r:
        e = r.entry("layers.0.w1")
    _flip_byte(path, e.offset + 5)
    with WeightFileReader(path) as r:
        with pytest.raises(ChecksumError) as ei:
            r.read_tensor("layers.0.w1")
        assert ei.value.tensor_name == "layers.0.w1"
        assert ei.value.offset == e.offset
        # sibling tensors still verify and read fine
        r.read_tensor("layers.1.w1")
        r.read_tensor_rows("layers.0.wq", slice(0, 8))


def test_bitflip_caught_by_verify_report(tmp_path):
    path, _, _ = _write(tmp_path)
    with WeightFileReader(path) as r:
        e = r.entry("layers.1.w2")
    _flip_byte(path, e.offset)
    with WeightFileReader(path) as r:
        report = r.verify()
    assert not report["ok"]
    assert [f["name"] for f in report["failures"]] == ["layers.1.w2"]
    assert report["failures"][0]["offset"] == e.offset


def test_row_band_read_verifies_only_touched_bands(tmp_path):
    """Band-granular lazy verify: a row read CRC-checks exactly the row bands
    it overlaps.  Corruption outside the read stripe does not fail that read,
    but IS caught the moment the corrupt band is touched — and always by a
    full ``verify()``."""
    path, _, _ = _write(tmp_path)
    with WeightFileReader(path) as r:
        e = r.entry("layers.0.w1")
        assert r.band_crcs is not None
        band = r.band_rows
    assert e.d > band, "fixture tensor must span at least two row bands"
    _flip_byte(path, e.offset + e.nbytes - 1)  # last byte: in the LAST band
    with WeightFileReader(path) as r:
        # Rows 0..8 live in band 0 — clean, so the read succeeds.
        r.read_tensor_rows("layers.0.w1", slice(0, 8))
        # Touching the corrupt band raises.
        with pytest.raises(ChecksumError):
            r.read_tensor_rows("layers.0.w1", slice(e.d - 1, e.d))
    # And an offline verify always catches it, whole-file or sharded onto
    # the shard that owns the tail rows.
    with WeightFileReader(path) as r:
        report = r.verify()
        assert not report["ok"]
        assert "layers.0.w1" in [f["name"] for f in report["failures"]]
    with WeightFileReader(path) as r:
        report = r.verify(shard=(1, 2))
        assert not report["ok"]
        assert any(f["name"] == "layers.0.w1" and "band" in f
                   for f in report["failures"])


def test_sharded_verify_clean_covers_file(tmp_path):
    """Every shard of a clean file verifies, each checking a nonzero slice
    of the row-band table — the cooperative-cluster verify contract."""
    path, _, _ = _write(tmp_path)
    with WeightFileReader(path) as r:
        assert r.band_crcs is not None
        total = 0
        for i in range(3):
            report = r.verify(shard=(i, 3))
            assert report["ok"] and report["row_band"] == r.band_rows
            assert report["bands_checked"] > 0
            total += report["bands_checked"]
        # shards overlap only where a band straddles a stripe edge, so the
        # union is at least every band once
        assert total >= sum(
            (e.d + r.band_rows - 1) // r.band_rows for e in r.entries)


def test_lazy_verify_env_opt_out(tmp_path, monkeypatch):
    path, _, _ = _write(tmp_path)
    with WeightFileReader(path) as r:
        e = r.entry("layers.0.w1")
    _flip_byte(path, e.offset + 5)
    monkeypatch.setenv("DLLAMA_WEIGHTS_VERIFY", "0")
    with WeightFileReader(path) as r:
        r.read_tensor("layers.0.w1")  # opted out: no raise
        assert not r.verify()["ok"]  # explicit verify still catches it


def test_empty_file_rejected(tmp_path):
    path = tmp_path / "empty.m"
    path.write_bytes(b"")
    with pytest.raises(FormatError, match="empty"):
        WeightFileReader(str(path))


def test_bad_magic_rejected(tmp_path):
    path = tmp_path / "junk.m"
    path.write_bytes(b"\xde\xad\xbe\xef" + b"\x00" * 64)
    with pytest.raises(FormatError, match="magic"):
        WeightFileReader(str(path))


def test_header_shorter_than_magic_rejected():
    with pytest.raises(FormatError, match="too short"):
        parse_header(b"\x01\x02")


def test_negative_dim_rejected():
    spec = tiny_spec()
    spec.dim = -64
    with pytest.raises(FormatError, match="dim"):
        parse_header(write_header(spec) + b"\x00" * 64)


def test_zero_layers_rejected():
    spec = tiny_spec()
    spec.n_layers = 0
    with pytest.raises(FormatError, match="n_layers"):
        parse_header(write_header(spec) + b"\x00" * 64)


def test_unknown_float_type_rejected():
    spec = tiny_spec()
    spec.weights_float_type = 9
    with pytest.raises(FormatError, match="weightsFloatType"):
        parse_header(write_header(spec) + b"\x00" * 64)


def test_unknown_header_key_rejected():
    raw = bytearray(write_header(tiny_spec()))
    # overwrite the first KV pair's key with a key id that does not exist
    struct.pack_into("<i", raw, 8, 999)
    with pytest.raises(FormatError, match="unknown header key"):
        parse_header(bytes(raw) + b"\x00" * 64)


def test_header_size_past_eof_rejected():
    raw = bytearray(write_header(tiny_spec()))
    struct.pack_into("<i", raw, 4, 8 + 8 * 200)  # valid shape, beyond EOF
    with pytest.raises(FormatError, match="past|truncated"):
        parse_header(bytes(raw), file_size=len(raw))


def test_header_size_unaligned_rejected():
    raw = bytearray(write_header(tiny_spec()))
    struct.pack_into("<i", raw, 4, 8 + 12)  # not whole (key, value) pairs
    with pytest.raises(FormatError, match="headerSize"):
        parse_header(bytes(raw))


# ---------------------------------------------------------------------------
# cli verify
# ---------------------------------------------------------------------------

def test_cli_verify_clean_corrupt_and_json(tmp_path, capsys):
    from dllama_tpu.cli import run_verify

    path, _, _ = _write(tmp_path)
    assert run_verify(Namespace(model=path, json=False)) == 0
    assert "checksums OK" in capsys.readouterr().out

    with WeightFileReader(path) as r:
        e = r.entry("layers.0.wq")
    _flip_byte(path, e.offset + 3)
    assert run_verify(Namespace(model=path, json=False)) == 1
    out = capsys.readouterr().out
    assert "layers.0.wq" in out and str(e.offset) in out

    assert run_verify(Namespace(model=path, json=True)) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["failures"][0]["name"] == "layers.0.wq"

    # structural rejection (truncation) also exits 1 and names the tensor
    with open(path, "r+b") as f:
        f.truncate(e.offset + 1)
    assert run_verify(Namespace(model=path, json=False)) == 1
    assert "truncated" in capsys.readouterr().out


def test_cli_verify_legacy_file_warns_but_passes(tmp_path, capsys):
    from dllama_tpu.cli import run_verify

    path, _, _ = _write(tmp_path, checksums=False)
    assert run_verify(Namespace(model=path, json=False)) == 0
    assert "UNVERIFIED" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Fault seams: weights_open / weights_read drills
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_fault_weights_open_truncate(tmp_path):
    path, _, _ = _write(tmp_path)
    # drop enough bytes to cut into the LAST tensor (past the ~112-byte
    # integrity section), so the open-time size check trips
    faults.install("weights_open:truncate:drop=4096")
    with pytest.raises(FormatError, match="truncated"):
        WeightFileReader(path)
    faults.clear()
    with WeightFileReader(path) as r:  # no fault: same file opens clean
        assert r.verify()["ok"]


@pytest.mark.faults
def test_fault_weights_read_bitflip(tmp_path):
    path, _, _ = _write(tmp_path)
    faults.install("weights_read:bitflip:byte=7,times=1")
    with WeightFileReader(path) as r:
        with pytest.raises(ChecksumError) as ei:
            r.read_tensor("token_embedding")
        assert ei.value.tensor_name == "token_embedding"
        # the flip was applied to a COPY and the budget (times=1) is spent:
        # the same tensor now reads clean from the pristine mmap
        r.read_tensor("token_embedding")
    faults.clear()


# ---------------------------------------------------------------------------
# Numeric-health watchdog: solo fail-fast, batch row_health, quarantine
# ---------------------------------------------------------------------------

from dllama_tpu.models import llama  # noqa: E402
from dllama_tpu.runtime.generate import Engine, NumericHealthError  # noqa: E402
from dllama_tpu.runtime.sampler import SamplerConfig  # noqa: E402
from tests.test_continuous_batching import CFG, _drain, _solo  # noqa: E402


@pytest.mark.faults
def test_solo_generate_fails_fast_on_nonfinite_logits():
    params = llama.random_params(CFG, seed=0, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    clean = [t for t, _ in eng.generate([5, 9, 3], steps=6)]
    assert len(clean) == 6
    # poison the THIRD decode dispatch: the first two decode tokens must
    # still be emitted, then the generator raises instead of yielding junk
    faults.install("logits:nan:after=2")
    got = []
    with pytest.raises(NumericHealthError, match="decode position"):
        for t, _ in eng.generate([5, 9, 3], steps=6):
            got.append(t)
    faults.clear()
    # prefix before the blowup is the clean stream; the poisoned token is
    # never emitted
    assert got == clean[: len(got)]
    assert len(got) < 6


@pytest.mark.faults
def test_numeric_checks_off_engine_does_not_raise():
    params = llama.random_params(CFG, seed=0, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0),
                 numeric_checks=False)
    faults.install("logits:nan")
    toks = [t for t, _ in eng.generate([5, 9, 3], steps=4)]
    faults.clear()
    assert len(toks) == 4  # no watchdog: garbage flows (the A/B baseline)


@pytest.mark.faults
def test_generate_batch_row_health_flags_only_poisoned_row():
    params = llama.random_params(CFG, seed=1, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    clean = eng.generate_batch([[5, 9, 3], [7]], steps=8)
    assert eng.row_health == [True, True]
    faults.install("logits:nan:row=0")
    got = eng.generate_batch([[5, 9, 3], [7]], steps=8)
    faults.clear()
    assert eng.row_health == [False, True]
    assert got[1] == clean[1]  # the healthy row is untouched


@pytest.mark.faults
def test_quarantine_siblings_bit_identical_and_slot_reusable():
    """THE acceptance test: a poisoned pool row finishes "error" while its
    siblings' streams stay bit-identical to a clean run, and the
    quarantined slab admits a fresh healthy row afterwards."""
    params = llama.random_params(CFG, seed=2, dtype=np.float32)
    samplers = [SamplerConfig(temperature=0.9, topp=0.95, seed=7),
                SamplerConfig(temperature=0.0, seed=1),
                SamplerConfig(temperature=1.3, topp=0.8, seed=42)]
    prompts = [[5, 9, 3], [7], [1, 2, 3, 4, 5, 6, 11]]

    def pool_run(poison):
        eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
        if poison:
            faults.install("logits:nan:row=1")
        sess = eng.batch_session(max_batch=3, chunk=4)
        slots = [sess.admit(list(p), steps=12, sampler=s)
                 for p, s in zip(prompts, samplers)]
        toks = _drain(sess, slots)
        fins = [sess.finish_reason(b) for b in slots]
        faults.clear()
        return sess, slots, [toks[b] for b in slots], fins

    sess, slots, clean, clean_fins = pool_run(poison=False)
    sess.close()
    assert clean_fins == ["length", "length", "length"]

    sess, slots, poisoned, fins = pool_run(poison=True)
    assert fins[1] == "error"  # quarantined, typed
    assert poisoned[1] == []   # poisoned from the first chunk: no output
    assert poisoned[0] == clean[0] and poisoned[2] == clean[2]  # bit-identical

    # the slab is FREE and healthy after release: a fresh row admitted into
    # it matches its solo stream
    sess.release(slots[1])
    reuse = sess.admit([7], steps=10,
                       sampler=SamplerConfig(temperature=0.8, seed=11))
    assert reuse == slots[1]
    got = _drain(sess, [reuse])[reuse]
    sess.close()
    assert got == _solo(params, [7], 10, SamplerConfig(temperature=0.8, seed=11))


def test_row_cancel_mid_verify_preserves_siblings():
    """ROADMAP follow-up: the batched-speculation fast path honors
    cancellation between verify launches — the cancelled row stops early,
    the surviving rows' streams are unchanged."""
    params = llama.random_params(CFG, seed=1, dtype=np.float32)
    prompts = [[5, 9, 3, 5, 9, 3, 5, 9], [7, 7, 7, 7, 7]]

    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    clean, _ = eng.generate_batch_spec(prompts, steps=12, draft_len=4)
    assert len(clean[0]) == 12

    emitted = [0, 0]

    def on_step(fresh):
        for b, burst in enumerate(fresh):
            emitted[b] += len(burst)

    eng2 = Engine(CFG, params, SamplerConfig(temperature=0.0))
    got, _ = eng2.generate_batch_spec(
        prompts, steps=12, draft_len=4, on_step=on_step,
        row_cancel=lambda b: b == 0 and emitted[0] >= 1)
    assert got[0] == clean[0][: len(got[0])]  # stopped at a launch boundary
    assert len(got[0]) < len(clean[0])        # actually cancelled early
    assert got[1] == clean[1]                 # sibling row unchanged


# ---------------------------------------------------------------------------
# HTTP mapping: quarantine -> 500 / finish_reason "error"
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_http_solo_quarantine_500_then_recovers():
    import http.client

    from dllama_tpu.formats.tokenizer_file import TokenizerData
    from dllama_tpu.serving.api_server import ServerState, create_server
    from dllama_tpu.tokenizer.bpe import Tokenizer
    from tests.test_llama_forward import tiny_cfg

    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [b"<0x%02X>" % b for b in range(256)]
    vocab += [b" ", b"e", b"t", b"he", b" the", b"hello", b" world"]
    scores = [0.0] * 259 + [-1.0, -2.0, -2.0, -1.5, -1.2, -1.1, -1.1]
    tok = Tokenizer(TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2))
    cfg = tiny_cfg(vocab_size=tok.vocab_size, seq_len=512, dim=32, kv_dim=16,
                   head_size=8, hidden_dim=64)
    params = llama.random_params(cfg, seed=13)
    engine = Engine(cfg, params, SamplerConfig(temperature=0.0, seed=1))
    state = ServerState(engine, tok, cfg, model_name="tiny-test",
                        template="llama3")
    srv = create_server(state, host="127.0.0.1", port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]

    def ask(body):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request("POST", "/v1/chat/completions", body=json.dumps(body),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        return resp.status, data

    body = {"model": "tiny-test", "temperature": 0.0, "max_tokens": 6,
            "messages": [{"role": "user", "content": "hello world"}]}
    try:
        # first decode dispatch poisoned, once: this request 500s
        faults.install("logits:nan:times=1")
        status, data = ask(body)
        assert status == 500
        assert b"non-finite" in data
        # the engine is NOT poisoned state-wise: the next request is clean
        status, data = ask(body)
        assert status == 200
        assert json.loads(data)["choices"][0]["finish_reason"] in (
            "stop", "length")
        # streaming: the quarantine surfaces as finish_reason "error"
        faults.install("logits:nan:times=1")
        status, data = ask(dict(body, stream=True))
        assert status == 200  # headers were already on the wire
        assert b'"finish_reason": "error"' in data
        assert b"data: [DONE]" in data
    finally:
        faults.clear()
        srv.shutdown()
