"""Test env: force JAX onto CPU with 8 virtual devices so multi-chip sharding
paths (tensor/data/sequence parallel) are exercised without TPU hardware —
the gap the reference left (it has no automated distributed tests, SURVEY.md §4).

Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
