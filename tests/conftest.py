"""Test env: force JAX onto CPU with 8 virtual devices so multi-chip sharding
paths (tensor/data/sequence parallel) are exercised without TPU hardware —
the gap the reference left (it has no automated distributed tests, SURVEY.md §4).

Note: this container's sitecustomize imports jax at interpreter start and
points it at the real TPU tunnel, so setting JAX_PLATFORMS here is too late —
we must go through jax.config. XLA_FLAGS still works because the CPU backend
only initializes on first use.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run")
    config.addinivalue_line(
        "markers",
        "faults: fault-injection chaos tests (CI smoke job: -m faults)")
