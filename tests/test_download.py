"""Downloader robustness: retry with backoff on transient errors, HTTP Range
resume from a partial ``.part`` file, atomic rename on completion — against a
local HTTP server that misbehaves on demand (no network needed)."""

import http.server
import threading
import urllib.error

import pytest

from dllama_tpu.convert.download import download_file

pytestmark = pytest.mark.faults

PAYLOAD = bytes(range(256)) * 64  # 16 KiB, recognizable at any offset


class _FlakyHandler(http.server.BaseHTTPRequestHandler):
    fails_left = 0  # 503s served before behaving
    short_next = False  # declare the full length but send only half, once
    lie_total = 0  # nonzero: Content-Range declares this (wrong) full size
    hits = 0
    ranges_seen: list = []

    def log_message(self, *args):
        pass

    def do_GET(self):
        cls = type(self)
        cls.hits += 1
        if self.path == "/missing":
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        if cls.fails_left > 0:
            cls.fails_left -= 1
            self.send_response(503)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        start = 0
        rng = self.headers.get("Range")
        if rng:
            cls.ranges_seen.append(rng)
            start = int(rng.split("=", 1)[1].rstrip("-"))
            if start >= len(PAYLOAD):
                self.send_response(416)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            self.send_response(206)
            total = cls.lie_total or len(PAYLOAD)
            self.send_header(
                "Content-Range", f"bytes {start}-{len(PAYLOAD) - 1}/{total}")
        else:
            self.send_response(200)
        body = PAYLOAD[start:]
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if cls.short_next:
            # premature EOF: HTTP/1.0 closes the socket after the handler
            # returns, and a chunked read() then sees b"" — byte-for-byte
            # indistinguishable from completion at the stream level
            cls.short_next = False
            self.wfile.write(body[: len(body) // 2])
            return
        self.wfile.write(body)


@pytest.fixture()
def local_http():
    _FlakyHandler.fails_left = 0
    _FlakyHandler.short_next = False
    _FlakyHandler.lie_total = 0
    _FlakyHandler.hits = 0
    _FlakyHandler.ranges_seen = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _FlakyHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def test_download_plain(local_http, tmp_path):
    dest = tmp_path / "model.m"
    download_file(f"http://127.0.0.1:{local_http}/model.m", str(dest))
    assert dest.read_bytes() == PAYLOAD
    assert not (tmp_path / "model.m.part").exists()  # renamed, not copied


def test_download_retries_transient_503(local_http, tmp_path):
    _FlakyHandler.fails_left = 2
    dest = tmp_path / "model.m"
    download_file(f"http://127.0.0.1:{local_http}/model.m", str(dest),
                  retries=4, backoff_s=0.01)
    assert dest.read_bytes() == PAYLOAD
    assert _FlakyHandler.hits == 3  # 2 failures + 1 success


def test_download_resumes_from_partial(local_http, tmp_path):
    dest = tmp_path / "model.m"
    (tmp_path / "model.m.part").write_bytes(PAYLOAD[:5000])
    download_file(f"http://127.0.0.1:{local_http}/model.m", str(dest),
                  retries=1, backoff_s=0.01)
    assert _FlakyHandler.ranges_seen == ["bytes=5000-"]
    assert dest.read_bytes() == PAYLOAD  # stitched, not restarted


def test_download_416_means_already_complete(local_http, tmp_path):
    dest = tmp_path / "model.m"
    (tmp_path / "model.m.part").write_bytes(PAYLOAD)  # fully fetched .part
    download_file(f"http://127.0.0.1:{local_http}/model.m", str(dest),
                  retries=1, backoff_s=0.01)
    assert dest.read_bytes() == PAYLOAD


def test_download_fails_fast_on_404(local_http, tmp_path):
    with pytest.raises(urllib.error.HTTPError):
        download_file(f"http://127.0.0.1:{local_http}/missing",
                      str(tmp_path / "x"), retries=5, backoff_s=0.01)
    assert _FlakyHandler.hits == 1  # 404 is not retried


def test_download_exhausted_retries_keeps_partial(local_http, tmp_path):
    _FlakyHandler.fails_left = 99
    dest = tmp_path / "model.m"
    with pytest.raises(RuntimeError, match="download failed"):
        download_file(f"http://127.0.0.1:{local_http}/model.m", str(dest),
                      retries=2, backoff_s=0.01)
    assert not dest.exists()
    assert _FlakyHandler.hits == 3  # initial try + 2 retries


def test_download_short_read_detected_and_resumed(local_http, tmp_path):
    """A premature EOF reads exactly like completion at the stream level —
    only the declared-size check catches it. The short torso must NOT be
    renamed into place; the retry resumes from the bytes on disk."""
    _FlakyHandler.short_next = True
    dest = tmp_path / "model.m"
    download_file(f"http://127.0.0.1:{local_http}/model.m", str(dest),
                  retries=2, backoff_s=0.01)
    assert dest.read_bytes() == PAYLOAD
    assert _FlakyHandler.hits == 2
    assert _FlakyHandler.ranges_seen == [f"bytes={len(PAYLOAD) // 2}-"]


def test_download_overshoot_deletes_part_and_fails(local_http, tmp_path):
    """More bytes on disk than the server's declared total: resuming cannot
    fix that, so the `.part` is deleted and the download fails loudly
    instead of renaming a corrupt file into place."""
    _FlakyHandler.lie_total = len(PAYLOAD) // 2
    dest = tmp_path / "model.m"
    (tmp_path / "model.m.part").write_bytes(PAYLOAD[:5000])
    with pytest.raises(RuntimeError, match="download corrupt"):
        download_file(f"http://127.0.0.1:{local_http}/model.m", str(dest),
                      retries=3, backoff_s=0.01)
    assert _FlakyHandler.hits == 1  # corruption is terminal, not retried
    assert not dest.exists()
    assert not (tmp_path / "model.m.part").exists()


def test_download_sha256_verified_ok(local_http, tmp_path):
    import hashlib

    dest = tmp_path / "model.m"
    download_file(f"http://127.0.0.1:{local_http}/model.m", str(dest),
                  expected_sha256=hashlib.sha256(PAYLOAD).hexdigest().upper())
    assert dest.read_bytes() == PAYLOAD  # case-insensitive digest accepted


def test_download_sha256_mismatch_deletes_part(local_http, tmp_path):
    dest = tmp_path / "model.m"
    with pytest.raises(RuntimeError, match="sha256"):
        download_file(f"http://127.0.0.1:{local_http}/model.m", str(dest),
                      retries=3, backoff_s=0.01, expected_sha256="0" * 64)
    assert _FlakyHandler.hits == 1  # corrupt bytes cannot be resumed
    assert not dest.exists()
    assert not (tmp_path / "model.m.part").exists()
