"""Chunked prefill + bucketed slot KV (the perf tentpole).

Two invariants under test. (1) Bit-identity: consuming a prompt in fixed
token-budget pieces — solo (``Engine.prefill(chunk=...)``) or pooled
(``admit_begin`` + ``prefill_step`` interleaved with ``step_chunk``) —
produces EXACTLY the logits/streams of monolithic prefill: each piece
writes its K/V before any later query attends, so causal masking makes the
split invisible. Migration between KV buckets carries the whole attended
slab plus the host sampler chain, so a row crossing buckets mid-stream is
equally invisible. (2) Capacity: under the same modeled HBM budget
(max_batch * seq_len KV token-slots), length-bucketed slot pools admit
STRICTLY more short rows than the uniform full-context slab — the reason
the bucketing exists.
"""

import numpy as np
import pytest

from dllama_tpu import faults
from dllama_tpu.models import llama
from dllama_tpu.models.config import ModelConfig
from dllama_tpu.runtime.generate import Engine
from dllama_tpu.runtime.sampler import SamplerConfig

CFG = ModelConfig(
    arch="llama", dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
    vocab_size=96, seq_len=64, head_size=16, kv_dim=32, dtype="float32",
)

LONG_PROMPT = [(i * 7 + 3) % 96 for i in range(23)]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _solo(params, prompt, steps, sampler=None):
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    return [t for t, _ in eng.generate(list(prompt), steps=steps,
                                       sampler=sampler)]


def _drain_interleaved(sess, out):
    """One prefill_step per step_chunk — the scheduler's tick — until every
    tracked slot is done; extends ``out`` in place."""
    while any(not sess.is_done(b) for b in out):
        sess.prefill_step()
        for b, burst in sess.step_chunk().items():
            if b in out:
                out[b].extend(burst)
    return out


# ---------------------------------------------------------------------------
# solo: chunked == monolithic, to the bit
# ---------------------------------------------------------------------------

def test_solo_chunked_prefill_logits_bit_identical():
    """Every chunk size (including ragged last pieces and chunk=1) must
    reproduce the monolithic final-position logits EXACTLY — the causal
    write-before-attend argument, checked to the bit. Cache contents are
    compared only over REAL positions: padded-tail slots hold whatever
    garbage the prefill bucket wrote, by design."""
    params = llama.random_params(CFG, seed=0, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    logits_mono, cache_mono = eng.prefill(eng.new_cache(), LONG_PROMPT)
    ref = np.asarray(logits_mono)
    n = len(LONG_PROMPT)
    for chunk in (1, 4, 7, 16, n, n + 5):
        logits, cache = eng.prefill(eng.new_cache(), LONG_PROMPT, chunk=chunk)
        assert np.array_equal(np.asarray(logits), ref), f"chunk={chunk}"
        for k in cache_mono:  # [L, S, kv, hd]: positions on axis 1
            a = np.asarray(cache[k])[:, :n]
            b = np.asarray(cache_mono[k])[:, :n]
            assert np.array_equal(a, b), f"chunk={chunk} cache[{k}]"


def test_solo_prefill_chunk_validation():
    params = llama.random_params(CFG, seed=0, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    with pytest.raises(ValueError):
        eng.prefill(eng.new_cache(), LONG_PROMPT, chunk=0)


# ---------------------------------------------------------------------------
# pooled: chunked admission under live neighbours, buckets, migration
# ---------------------------------------------------------------------------

def test_chunked_admission_bit_identical_with_resident_row():
    """The tentpole scenario: a long prompt admitted incrementally into a
    pool where a resident row KEEPS DECODING between prefill pieces. Both
    streams must equal their solo runs bit for bit — the resident row must
    not see the newcomer's prefill, and the newcomer's chunked cache must
    equal a monolithic one."""
    params = llama.random_params(CFG, seed=1, dtype=np.float32)
    s_res = SamplerConfig(temperature=0.9, topp=0.95, seed=7)
    s_new = SamplerConfig(temperature=1.2, topp=0.9, seed=23)
    want_res = _solo(params, [5, 9, 3], 16, s_res)
    want_new = _solo(params, LONG_PROMPT, 10, s_new)

    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    for bucket_kv in (False, True):
        sess = eng.batch_session(max_batch=3, chunk=4, bucket_kv=bucket_kv,
                                 min_bucket=8, prefill_chunk=5)
        got = {}
        res = sess.admit([5, 9, 3], steps=16, sampler=s_res)
        got[res] = []
        for b, burst in sess.step_chunk().items():  # resident row is 4 deep
            got[b].extend(burst)
        new = sess.admit_begin(LONG_PROMPT, steps=10, sampler=s_new)
        got[new] = []
        assert new in sess.pending_prefills
        # 22-token prefix at 5 tokens/tick: the row must stay mid-prefill
        # across several ticks while the resident row nets tokens each tick
        ticks_mid_prefill = 0
        while new in sess.pending_prefills:
            _, finished = sess.prefill_step()
            fresh = sess.step_chunk()
            if not finished:
                assert new not in fresh  # not live until the prefix completes
                ticks_mid_prefill += 1
            if res in fresh and fresh[res] == []:
                pytest.fail("resident row starved during prefill")
            for b, burst in fresh.items():
                got[b].extend(burst)
        assert ticks_mid_prefill >= 3
        _drain_interleaved(sess, got)
        assert sess.prefill_ms > 0.0
        sess.close()
        assert got[res] == want_res, f"bucket_kv={bucket_kv}"
        assert got[new] == want_new, f"bucket_kv={bucket_kv}"


def test_migration_preserves_stream_and_counts():
    """A tiny min_bucket forces rows through several bucket migrations
    mid-stream; tokens (sampled — the PRNG chain must survive the move)
    still equal solo, and the session counts the migrations."""
    params = llama.random_params(CFG, seed=2, dtype=np.float32)
    samplers = [SamplerConfig(temperature=1.1, topp=0.9, seed=5),
                SamplerConfig(temperature=0.0, seed=1)]
    prompts = [[9, 2, 4], [7]]
    want = [_solo(params, p, 30, s) for p, s in zip(prompts, samplers)]

    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    sess = eng.batch_session(max_batch=4, chunk=3, bucket_kv=True,
                             min_bucket=4, prefill_chunk=2)
    out = {}
    for p, s in zip(prompts, samplers):
        h = sess.admit_begin(p, steps=30, sampler=s)
        out[h] = []
    _drain_interleaved(sess, out)
    # rows reach position ~32 from 4-slot slabs: 4->8->16->32 per row
    assert sess.migrations >= 4
    got = [out[h] for h in sorted(out)]
    sess.close()
    assert got == want


def test_bucketed_pools_admit_strictly_more_rows():
    """The capacity acceptance bar: at the SAME modeled budget
    (max_batch * seq_len token-slots), short requests pack strictly more
    rows bucketed than uniform — uniform spends a full-context row per
    request regardless of length."""
    params = llama.random_params(CFG, seed=0, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))

    def admit_until_full(sess):
        n = 0
        while sess.can_admit(3, 4):  # short prompt, short completion
            sess.admit([5, 9, 3], steps=4)
            n += 1
        return n

    uni = eng.batch_session(max_batch=2, chunk=4)
    bkt = eng.batch_session(max_batch=2, chunk=4, bucket_kv=True,
                            min_bucket=8)
    n_uni = admit_until_full(uni)
    n_bkt = admit_until_full(bkt)
    assert uni.budget_tokens == bkt.budget_tokens
    assert n_uni == 2  # the uniform slab: one row per slot, length-blind
    assert n_bkt > n_uni  # 8-slot reservations pack 64/8 = 8 rows per slot
    # worst-case requests degrade gracefully TO the uniform count, never
    # below it: bucketing is a strict win
    full = eng.batch_session(max_batch=2, chunk=4, bucket_kv=True,
                             min_bucket=8)
    m = 0
    while full.can_admit(3, CFG.seq_len):
        full.admit_begin([5, 9, 3], steps=CFG.seq_len)
        m += 1
    assert m == 2
    for s in (uni, bkt, full):
        s.close()


def test_cancel_mid_prefill_frees_slot_and_budget():
    """Cancelling an admission whose prompt is still being consumed must
    drop the pending prefill immediately and, after release(), hand back
    the row AND the KV reservation — the slab is reusable by a successor
    whose stream still matches solo."""
    params = llama.random_params(CFG, seed=3, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    sess = eng.batch_session(max_batch=1, chunk=4, bucket_kv=True,
                             min_bucket=8, prefill_chunk=4)
    h = sess.admit_begin(LONG_PROMPT, steps=40)
    adv = sess.prefill_step()  # consume one piece, then abandon
    assert adv == (h, False)
    assert not sess.can_admit(3, 4)  # worst-case reservation holds the pool
    sess.cancel(h)
    assert sess.pending_prefills == []
    assert sess.is_done(h) and sess.finish_reason(h) is None
    assert sess.step_chunk() == {}  # cancelled row never decodes
    sess.release(h)
    assert sess.reserved_tokens == 0
    assert sess.can_admit(3, 4)
    scfg = SamplerConfig(temperature=0.8, seed=11)
    h2 = sess.admit([7], steps=10, sampler=scfg)
    out = _drain_interleaved(sess, {h2: []})[h2]
    sess.close()
    assert out == _solo(params, [7], 10, scfg)


def test_prefill_chunk_fault_seam():
    """The chaos seam: a fault planted at the prefill_chunk site fires
    inside prefill_step (typed, not a hang), and the admission survives —
    the cursor hasn't advanced, so a retry consumes the same piece and the
    stream still matches solo."""
    params = llama.random_params(CFG, seed=4, dtype=np.float32)
    scfg = SamplerConfig(temperature=0.0, seed=1)
    want = _solo(params, LONG_PROMPT, 6, scfg)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    sess = eng.batch_session(max_batch=1, chunk=4, prefill_chunk=6)
    h = sess.admit_begin(LONG_PROMPT, steps=6, sampler=scfg)
    faults.install("prefill_chunk:raise:times=1")
    with pytest.raises(faults.FaultInjected):
        sess.prefill_step()
    assert h in sess.pending_prefills  # still admitted, still resumable
    out = _drain_interleaved(sess, {h: []})[h]
    sess.close()
    assert out == want
