"""Quantized MoE expert stacks x tensor parallelism.

The reference's flagship configuration is Q40 Grok-1/Mixtral with every node
holding a 1/n slice of EVERY expert (`/root/reference/src/transformer.cpp:479-487`,
expert matmuls on slices at `/root/reference/src/grok1-tasks.cpp:128-143`).
These tests assert the TPU equivalent — expert planes output-sharded under
shard_map (parallel.quant_tp) — decodes identically to the single-device
engine on the 8-virtual-device CPU mesh, and that the small-T
selected-experts path (decode AND speculative verify) engages exactly when
the union of routed experts is smaller than E.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models import llama, moe
from dllama_tpu.models.config import (
    GROK_EMBEDDING_SCALE,
    GROK_LOGIT_SCALE,
    ModelConfig,
)
from dllama_tpu.parallel import quant_tp
from dllama_tpu.parallel.mesh import tp_mesh
from dllama_tpu.runtime.generate import Engine
from dllama_tpu.runtime.sampler import SamplerConfig

MIXTRAL = ModelConfig(
    arch="mixtral", dim=256, hidden_dim=512, n_layers=2, n_heads=8,
    n_kv_heads=8, vocab_size=512, seq_len=64, head_size=32, kv_dim=256,
    n_experts=8, n_active_experts=2, rope_style="half", dtype="float32",
)

GROK = ModelConfig(
    arch="grok1", dim=256, hidden_dim=512, n_layers=2, n_heads=8,
    n_kv_heads=8, vocab_size=512, seq_len=64, head_size=32, kv_dim=256,
    n_experts=4, n_active_experts=2, hidden_act="gelu", rope_style="half",
    embedding_scale=GROK_EMBEDDING_SCALE, logit_scale=GROK_LOGIT_SCALE,
    post_norms=True, dtype="float32",
)


@pytest.fixture(scope="module")
def qp():
    dense = llama.random_params(MIXTRAL, seed=0, dtype=np.float32)
    return llama.quantize_params(dense, "q40")


def _single_device_logits(cfg, params, tokens):
    rope = llama.rope_tables(cfg)
    logits, _ = jax.jit(
        lambda p, r, c, t: llama.forward(cfg, p, r, t, c, jnp.int32(0))
    )(jax.tree.map(jnp.asarray, params), rope, llama.init_cache(cfg), tokens)
    return logits


@pytest.mark.parametrize("tp", [2, 8])
def test_moe_tp_forward_matches_single_device(qp, tp):
    """Decode (T=1, selected-experts path) and prefill (T=4, T*k >= E dense
    combine) both produce single-device logits under expert-sharded TP."""
    rope = llama.rope_tables(MIXTRAL)
    mesh = tp_mesh(tp)
    sharded = quant_tp.shard_quant_params(qp, mesh, MIXTRAL)
    fwd = jax.jit(quant_tp.make_tp_forward(MIXTRAL, mesh, sharded))
    for tokens in (jnp.asarray([5], jnp.int32),
                   jnp.asarray([5, 9, 3, 1], jnp.int32)):
        ref = _single_device_logits(MIXTRAL, qp, tokens)
        got, _ = fwd(sharded, rope, llama.init_cache(MIXTRAL), tokens,
                     jnp.int32(0))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4
        )


def test_grok_tp_forward_matches_single_device():
    """Grok-1 variant: post-norms + gelu + embedding/logit scales survive the
    shard_map expert sharding."""
    params = llama.quantize_params(
        llama.random_params(GROK, seed=3, dtype=np.float32), "q40"
    )
    tokens = jnp.asarray([7], jnp.int32)
    ref = _single_device_logits(GROK, params, tokens)
    mesh = tp_mesh(8)
    sharded = quant_tp.shard_quant_params(params, mesh, GROK)
    got, _ = jax.jit(quant_tp.make_tp_forward(GROK, mesh, sharded))(
        sharded, llama.rope_tables(GROK), llama.init_cache(GROK), tokens,
        jnp.int32(0),
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4
    )


def test_moe_specs_shard_every_expert_plane(qp):
    """Expert stacks must actually shard — replication is the failure mode
    that kept a Q40 Mixtral from fitting (round-3 verdict's #1 gap)."""
    specs = quant_tp.quant_param_specs(qp, MIXTRAL, 8)
    for name in ("moe_up", "moe_gate", "moe_down"):
        qt = specs["layers"][name]
        assert qt.w[-1] == "tp" and qt.s[-1] == "tp" and qt.s2[-1] == "tp", name
    # the router is tiny and replicated, like the root's copy in the reference
    assert all(s is None for s in specs["layers"]["moe_router"])


def test_moe_lane_padding_and_local_shards(qp):
    """moe_up/moe_gate pad their hidden output axis and moe_down its packed
    input to the same lane-aligned width (the w1/w3-vs-w2 contract), so the
    gathered per-expert hidden feeds the down matmul with no slicing; each
    device holds exactly 1/tp of every expert plane."""
    mesh = tp_mesh(8)
    sharded = quant_tp.shard_quant_params(qp, mesh, MIXTRAL)
    target = quant_tp.ffn_padded_width(MIXTRAL, "q40", 8)
    up = sharded["layers"]["moe_up"]
    assert up.w.shape[-1] == target
    assert up.w.addressable_shards[0].data.shape[-1] == target // 8
    down = sharded["layers"]["moe_down"]
    assert down.k_padded == target
    assert down.w.addressable_shards[0].data.shape[-1] == MIXTRAL.dim // 8


def test_moe_tp_engine_greedy_decode_invariance(qp):
    """Engine-level: greedy tokens from the expert-sharded quant-TP engine ==
    the single-device (fused moe_upgate) engine."""
    e1 = Engine(MIXTRAL, qp, SamplerConfig(temperature=0.0))
    t1, _, _ = e1.generate_fused([3, 7, 11], steps=8)
    e2 = Engine(MIXTRAL, qp, SamplerConfig(temperature=0.0), mesh=tp_mesh(8))
    t2, _, _ = e2.generate_fused([3, 7, 11], steps=8)
    assert t1 == t2


def test_verify_batch_uses_selected_experts_and_matches_dense(qp, monkeypatch):
    """A small-T batch (speculative verify shape) must take the
    selected-experts path — reading at most min(E, T*k) expert plane sets —
    and produce exactly the dense-combine logits. T rows whose union could
    cover every expert (T*k >= E) must take the dense path."""
    calls = []
    orig = moe._moe_decode_selected

    def spy(cfg, lp, xb, layer, *a, **k):
        calls.append(xb.shape[0])
        return orig(cfg, lp, xb, layer, *a, **k)

    monkeypatch.setattr(moe, "_moe_decode_selected", spy)

    toks8 = jnp.asarray([5, 9, 3, 1, 2, 4, 6, 7], jnp.int32)
    logits8 = _single_device_logits(MIXTRAL, qp, toks8)
    assert calls == []  # T*k = 16 >= E -> dense combine

    logits2 = _single_device_logits(MIXTRAL, qp, toks8[:2])
    # the layer scan traces its body once -> one recorded call, T=2
    assert calls == [2]
    # causal attention: rows 0..1 are unaffected by rows 2..7, so the
    # selected-experts path must reproduce the dense path's logits exactly
    np.testing.assert_allclose(
        np.asarray(logits2), np.asarray(logits8)[:2], rtol=1e-5, atol=1e-5
    )


def test_spec_decode_quant_moe_matches_plain(qp):
    """generate_spec on a quantized MoE: verify steps (T=3 here) ride the
    selected-experts path and the emitted stream equals plain decode."""
    plain = Engine(MIXTRAL, qp, SamplerConfig(temperature=0.0))
    want = [t for t, _ in plain.generate([1, 2, 3], steps=12)]
    spec = Engine(MIXTRAL, qp, SamplerConfig(temperature=0.0))
    got = [t for t, _ in spec.generate_spec([1, 2, 3], steps=12, draft_len=2)]
    assert got == want


def test_moe_wire_stats_analytic_bytes(qp):
    """Decode-step S/R for an expert-sharded MoE: 2 attention gathers (dim) +
    k hidden gathers (padded H') + 1 combined-output gather (dim) per layer,
    plus the padded f32 logits gather."""
    from dllama_tpu.ops.qmatmul import _pad_up

    eng = Engine(MIXTRAL, qp, SamplerConfig(temperature=0.0), mesh=tp_mesh(8))
    hidden = quant_tp.ffn_padded_width(MIXTRAL, "q40", 8)
    layer_feats = MIXTRAL.n_layers * (
        3 * MIXTRAL.dim + MIXTRAL.n_active_experts * hidden
    )
    vocab_bytes = _pad_up(MIXTRAL.vocab_size, 128 * 8) * 4.0
    want_kb = (layer_feats * 4.0 + vocab_bytes) * (7 / 8) / 1024.0
    assert abs(eng.wire_kb_per_token - want_kb) < 1e-9
    # a 9-row batch (spec verify / prefill): 9*k >= E routes the dense
    # combine, which gathers ALL E expert hiddens per row — wire_kb(rows)
    # must price E, not k (stats-accuracy finding, r4 review)
    feats9 = MIXTRAL.n_layers * (3 * MIXTRAL.dim + MIXTRAL.n_experts * hidden)
    want9 = (feats9 * 4.0 + vocab_bytes) * (7 / 8) / 1024.0 * 9
    assert abs(eng.wire_kb(9) - want9) < 1e-9
    # a 2-row batch stays on the selected path: union caps at 2*k experts
    feats2 = MIXTRAL.n_layers * (3 * MIXTRAL.dim + 4 * hidden)
    want2 = (feats2 * 4.0 + vocab_bytes) * (7 / 8) / 1024.0 * 2
    assert abs(eng.wire_kb(2) - want2) < 1e-9


def test_moe_quant_reader_streams_onto_mesh(tmp_path):
    """quant_params_from_reader(mesh=...) on a Q40 MoE file: expert planes
    land sharded (streamed layer-by-layer — the Grok-1-class load path) and
    the TP engine decodes identically to the host-loaded single-device one."""
    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.weights import tensor_plan, write_model, WeightFileReader
    from dllama_tpu.quants import blocks

    spec = ModelSpec(
        arch=ArchType.MIXTRAL, dim=MIXTRAL.dim, hidden_dim=MIXTRAL.hidden_dim,
        n_layers=MIXTRAL.n_layers, n_heads=MIXTRAL.n_heads,
        n_kv_heads=MIXTRAL.n_kv_heads, vocab_size=MIXTRAL.vocab_size,
        seq_len=MIXTRAL.seq_len, n_experts=MIXTRAL.n_experts,
        n_active_experts=MIXTRAL.n_active_experts,
        weights_float_type=blocks.Q40,
    )
    rng = np.random.default_rng(11)
    path = str(tmp_path / "mix_q40.m")
    write_model(
        path, spec,
        {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(np.float32)
         for e in tensor_plan(spec)},
    )
    mesh = tp_mesh(8)
    with WeightFileReader(path) as reader:
        cfg = type(MIXTRAL)(**{**MIXTRAL.__dict__})
        sharded = llama.quant_params_from_reader(reader, cfg, "q40", mesh=mesh)
    with WeightFileReader(path) as reader:
        host = llama.quant_params_from_reader(reader, cfg, "q40")

    up = sharded["layers"]["moe_up"]
    target = quant_tp.ffn_padded_width(cfg, "q40", 8)
    assert up.w.shape == (cfg.n_layers, cfg.n_experts,
                          host["layers"]["moe_upgate"].w.shape[-2], target)
    assert up.w.sharding.spec[-1] == "tp"
    assert up.w.addressable_shards[0].data.shape[-1] == target // 8

    e_tp = Engine(cfg, sharded, SamplerConfig(temperature=0.0), mesh=mesh)
    t_tp, _, _ = e_tp.generate_fused([3, 7, 11], steps=6)
    e_host = Engine(cfg, host, SamplerConfig(temperature=0.0))
    t_host, _, _ = e_host.generate_fused([3, 7, 11], steps=6)
    assert t_tp == t_host
