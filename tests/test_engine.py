"""Decode-engine tests: greedy determinism, prefill/decode equivalence,
chat-style continuation, sampler behavior."""

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.models import llama
from dllama_tpu.runtime.generate import Engine, prefill_bucket
from dllama_tpu.runtime.sampler import SamplerConfig, sample

from tests.test_llama_forward import tiny_cfg


def make_engine(temperature=0.0, seed=7, **cfg_kw):
    cfg = tiny_cfg(**cfg_kw)
    params = llama.random_params(cfg, seed=seed)
    return Engine(cfg, params, SamplerConfig(temperature=temperature, seed=3)), cfg


def test_greedy_generation_deterministic():
    eng, cfg = make_engine()
    prompt = [1, 5, 9]
    out1 = [t for t, _ in eng.generate(prompt, steps=8)]
    eng2, _ = make_engine()
    out2 = [t for t, _ in eng2.generate(prompt, steps=8)]
    assert out1 == out2
    assert len(out1) == 8
    assert all(0 <= t < cfg.vocab_size for t in out1)


def test_greedy_matches_unbatched_forward():
    """Engine (bucketed prefill + decode) must equal naive argmax decoding."""
    eng, cfg = make_engine()
    params = jax.tree.map(jnp.asarray, llama.random_params(cfg, seed=7))
    rope = llama.rope_tables(cfg)
    prompt = [1, 5, 9]

    toks = list(prompt)
    for _ in range(6):
        logits, _ = llama.forward(
            cfg, params, rope, jnp.asarray(toks, jnp.int32), llama.init_cache(cfg), 0
        )
        toks.append(int(np.argmax(np.asarray(logits[-1]))))
    want = toks[len(prompt):]

    got = [t for t, _ in eng.generate(prompt, steps=6)]
    assert got == want


def test_single_token_prompt():
    eng, cfg = make_engine()
    out = [t for t, _ in eng.generate([2], steps=4)]
    assert len(out) == 4


def test_continuation_preserves_cache():
    """Two-turn chat: continuing from final_session == one long prompt."""
    eng, cfg = make_engine()
    turn1 = [1, 4, 7]
    out1 = [t for t, _ in eng.generate(turn1, steps=3)]
    turn2 = [8, 2]
    out2 = [t for t, _ in eng.generate(turn2, steps=3, session=eng.final_session)]

    eng2, _ = make_engine()
    merged = turn1 + out1 + turn2
    out_ref = [t for t, _ in eng2.generate(merged, steps=3)]
    assert out2 == out_ref


def test_stop_tokens_halt_generation():
    eng, cfg = make_engine()
    all_toks = [t for t, _ in eng.generate([1, 5, 9], steps=10)]
    stop = all_toks[2]
    stopped = [t for t, _ in eng.generate([1, 5, 9], steps=10, stop_tokens=(stop,))]
    assert stopped == all_toks[: 3]
    assert eng.final_session.pending_token == stop  # stop token not yet consumed


def test_prefill_bucket():
    assert prefill_bucket(1) == 8
    assert prefill_bucket(8) == 8
    assert prefill_bucket(9) == 16
    assert prefill_bucket(9000) == 9000


def test_sampler_greedy_vs_topp():
    key = jax.random.PRNGKey(0)
    logits = jnp.asarray([0.1, 3.0, 0.2, 2.9, -1.0])
    assert int(sample(logits, key, SamplerConfig(temperature=0.0))) == 1
    # top-p with tiny p keeps only the argmax
    assert int(sample(logits, key, SamplerConfig(temperature=0.5, topp=1e-6))) == 1
    # temperature sampling stays within the nucleus for moderate topp
    counts = set()
    for i in range(20):
        k = jax.random.PRNGKey(i)
        counts.add(int(sample(logits, k, SamplerConfig(temperature=1.0, topp=0.9))))
    assert counts <= {1, 3}  # two dominant logits hold >0.9 mass


def test_steps_clamped_to_seq_len():
    eng, cfg = make_engine()
    out = [t for t, _ in eng.generate([1, 2, 3], steps=10_000)]
    assert len(out) == cfg.seq_len - 3


def test_prefill_bucket_clamped_to_seq_len():
    """Prompt near the context boundary: padded bucket must not exceed seq_len
    (an out-of-range cache write would be silently clamped by XLA)."""
    eng, cfg = make_engine(seq_len=24)
    out = [t for t, _ in eng.generate(list(range(1, 21)), steps=4)]
    assert len(out) == 4

    # and the result must match a roomier model config (same math, bigger cache)
    eng2, _ = make_engine(seq_len=64)
    out2 = [t for t, _ in eng2.generate(list(range(1, 21)), steps=4)]
    assert out == out2


def test_steps_zero_yields_nothing():
    eng, _ = make_engine()
    out = [t for t, _ in eng.generate([1, 2, 3], steps=0)]
    assert out == []
    assert eng.final_session.pos == 3
    assert eng.final_session.pending_token is None


def test_fused_decode_matches_stepwise():
    """The on-device fused loop must produce the same greedy stream as the
    host-stepped loop."""
    eng, cfg = make_engine()
    want = [t for t, _ in eng.generate([1, 5, 9], steps=8)]
    eng2, _ = make_engine()
    got, prefill_ms, decode_ms = eng2.generate_fused([1, 5, 9], steps=8)
    assert got == want
    # 3 prompt + 7 consumed generated tokens in cache; the 8th is pending
    assert eng2.final_session.pos == 3 + 7
    assert eng2.final_session.pending_token == got[-1]


def test_fused_decode_steps_zero_and_pending():
    eng, cfg = make_engine()
    out, _, _ = eng.generate_fused([1, 5, 9], steps=0)
    assert out == []
    assert eng.final_session.pending_token is None

    eng2, _ = make_engine()
    out1, _, _ = eng2.generate_fused([1, 5, 9], steps=1)
    assert len(out1) == 1
    # the prefill-sampled token is pending: continuation must consume it
    assert eng2.final_session.pending_token == out1[0]
    cont = [t for t, _ in eng2.generate([7], steps=2, session=eng2.final_session)]

    eng3, _ = make_engine()
    ref = [t for t, _ in eng3.generate([1, 5, 9] + out1 + [7], steps=2)]
    assert cont == ref


def test_fused_decode_chunked_long_run():
    """steps > DECODE_CHUNK spans multiple fused chunks, including a truncated
    final one — stream must still match the host-stepped loop."""
    eng, cfg = make_engine(seq_len=128)
    want = [t for t, _ in eng.generate([1, 5, 9], steps=90)]
    eng2, _ = make_engine(seq_len=128)
    got, _, _ = eng2.generate_fused([1, 5, 9], steps=90)
    assert got == want
    # 3 prompt + 89 consumed generated tokens; the 90th is pending
    assert eng2.final_session.pos == 3 + 90 - 1
    # continuation across the truncation boundary stays exact
    cont = [t for t, _ in eng2.generate([7], steps=3, session=eng2.final_session)]
    ref_eng, _ = make_engine(seq_len=128)
    ref = [t for t, _ in ref_eng.generate([1, 5, 9] + got + [7], steps=3)]
    assert cont == ref


def test_cli_parser_worker_and_multihost_flags():
    """CLI surface parity: worker mode + multi-host topology flags parse; a
    coordinator without host identity is rejected (cli.maybe_init_distributed)."""
    import pytest as _pytest

    from dllama_tpu import cli

    p = cli.build_parser()
    args = p.parse_args(
        ["worker", "--model", "m.m", "--tokenizer", "t.t",
         "--coordinator", "h:1234", "--num-hosts", "2", "--host-id", "1"]
    )
    assert args.mode == "worker" and args.host_id == 1

    incomplete = p.parse_args(
        ["generate", "--model", "m.m", "--tokenizer", "t.t", "--coordinator", "h:1"]
    )
    with _pytest.raises(SystemExit):
        cli.maybe_init_distributed(incomplete)

    # no topology flags -> single host, no jax.distributed call
    plain = p.parse_args(["generate", "--model", "m.m", "--tokenizer", "t.t"])
    assert cli.maybe_init_distributed(plain) == 0


def test_token_stats_split_inference_from_transfer():
    """The I/T split must be real: inference (device-wait) + transfer
    (host+dispatch) partition generation time, and inference is not just a
    copy of G (the round-2 verdict's cosmetic-split finding). Reference
    surface: `/root/reference/src/apps/dllama/dllama.cpp:74-75`."""
    eng, _ = make_engine()
    stats = [s for _, s in eng.generate([1, 2, 3], steps=6)]
    decode_stats = stats[1:]  # first entry reports the prefill
    assert decode_stats
    for s in decode_stats:
        assert s.inference_ms >= 0 and s.transfer_ms >= 0
        assert abs((s.inference_ms + s.transfer_ms) - s.generation_ms) < 0.5
    # dispatch overhead exists on every backend: at least one token must show
    # a nonzero transfer component distinct from generation time
    assert any(s.transfer_ms > 0 for s in decode_stats)
    assert any(abs(s.inference_ms - s.generation_ms) > 1e-9 for s in decode_stats)


def test_generate_fused_seed_reproducible():
    """generate_fused with an explicit sampler must be reproducible per
    seed (its own chain, not the engine chain) — r5 review caught the
    closure being built but never called."""
    import numpy as np

    from dllama_tpu.models import llama
    from dllama_tpu.models.config import ModelConfig
    from dllama_tpu.runtime.generate import Engine
    from dllama_tpu.runtime.sampler import SamplerConfig

    cfg = ModelConfig(
        arch="llama", dim=64, hidden_dim=128, n_layers=2, n_heads=4,
        n_kv_heads=2, vocab_size=96, seq_len=64, head_size=16, kv_dim=32,
        dtype="float32",
    )
    params = llama.random_params(cfg, seed=0, dtype=np.float32)
    eng = Engine(cfg, params, SamplerConfig(temperature=0.0))
    s = SamplerConfig(temperature=0.9, topp=0.95, seed=7)
    a, _, _ = eng.generate_fused([1, 5, 9], steps=8, sampler=s)
    b, _, _ = eng.generate_fused([1, 5, 9], steps=8, sampler=s)
    assert a == b and len(a) == 8
    c, _, _ = eng.generate_fused(
        [1, 5, 9], steps=8, sampler=SamplerConfig(temperature=0.9,
                                                  topp=0.95, seed=8))
    assert c != a  # a different seed draws a different stream
