"""Mid-stream decode failover (the PR 12 tentpole).

Four layers under test. (1) The checkpoint frames: a stream that opts
in via ``X-Dllama-Ckpt`` interleaves ``event: dllama-ckpt`` control
frames whose payloads decode to resumable snapshots (splice offset,
UTF-8 decoder state, sampler chain position) without perturbing the
client-visible bytes. (2) The replica resume endpoint:
``POST /v1/kv/resume`` continues a checkpointed stream BYTE-identically
— the raw continuation equals the original stream's visible bytes from
the splice offset on, for every checkpoint taken, on a cold or a warm
(same prompt already served) sibling, stop-string sessions included.
(3) The router orchestration: an upstream death mid-SSE resumes on a
sibling behind the same client connection (outcome="ok"), and every
fallback-matrix row — injected / no_ckpt / stale_ckpt / admit_failed /
exhausted — terminates with a typed SSE error event plus ``[DONE]``,
never a bare TCP cut, each counted in
``dllama_stream_resume_total``. (4) The bounded checkpoint store: LRU
eviction, get-touches, pop-on-completion.

The ``ckpt_write`` and ``resume`` fault seams are exercised by name
(FAULT-004)."""

import base64
import codecs
import http.client
import json
import threading

import pytest

from dllama_tpu import faults
from dllama_tpu.formats.tokenizer_file import TokenizerData
from dllama_tpu.models import llama
from dllama_tpu.runtime.generate import Engine
from dllama_tpu.runtime.sampler import SamplerConfig
from dllama_tpu.serving import kv_transfer
from dllama_tpu.serving import router as router_mod
from dllama_tpu.serving.api_server import ServerState, create_server
from dllama_tpu.tokenizer.bpe import Tokenizer

from tests.test_llama_forward import tiny_cfg

OUTCOMES = ("ok", "no_ckpt", "stale_ckpt", "admit_failed", "no_replica",
            "injected", "exhausted")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _make_tokenizer():
    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [b"<0x%02X>" % b for b in range(256)]
    vocab += [b" ", b"e", b"t", b"he", b" the", b"hello", b" world"]
    scores = [0.0] * 259 + [-1.0, -2.0, -2.0, -1.5, -1.2, -1.1, -1.1]
    return Tokenizer(TokenizerData(vocab=vocab, scores=scores,
                                   bos_id=1, eos_id=2))


@pytest.fixture(scope="module")
def pair():
    """Two in-process replica servers over the SAME tiny weights (so a
    resumed row regenerates the dead replica's tokens exactly)."""
    tok = _make_tokenizer()
    cfg = tiny_cfg(vocab_size=tok.vocab_size, seq_len=512, dim=32,
                   kv_dim=16, head_size=8, hidden_dim=64)
    params = llama.random_params(cfg, seed=13)
    servers = []
    ports = []
    for _ in range(2):
        engine = Engine(cfg, params, SamplerConfig(temperature=0.0, seed=1))
        state = ServerState(engine, tok, cfg, model_name="tiny-test",
                            template="llama3", batch_window_ms=5.0,
                            batch_chunk=2, kv_pages=16, ckpt_interval=2)
        srv = create_server(state, host="127.0.0.1", port=0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        ports.append(srv.server_address[1])
    yield ports
    for srv in servers:
        srv.shutdown()


def _post(port, path, body, headers=None, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path,
                     body if isinstance(body, bytes)
                     else json.dumps(body).encode(),
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _chat(max_tokens=12, **kw):
    body = {"model": "m", "max_tokens": max_tokens, "temperature": 0.0,
            "stream": True,
            "messages": [{"role": "user", "content": "hello world"}]}
    body.update(kw)
    return body


def _split_stream(data: bytes):
    """-> (visible_bytes, [(offset, payload_bytes), ...]): the client's
    view with ckpt control frames stripped, plus the decoded frames."""
    visible, frames = [], []
    for ev in data.split(b"\n\n"):
        if not ev:
            continue
        if ev.startswith(b"event: dllama-ckpt"):
            line = next(ln for ln in ev.split(b"\n")
                        if ln.startswith(b"data: "))
            off, _, b64 = line[6:].partition(b" ")
            frames.append((int(off), base64.b64decode(b64)))
        else:
            visible.append(ev + b"\n\n")
    return b"".join(visible), frames


def _parts(data: bytes):
    """-> (content_text, finish_reason, saw_done, error_message)."""
    text, finish, done, err = [], None, False, None
    for line in data.split(b"\n"):
        if not line.startswith(b"data: "):
            continue
        if line == b"data: [DONE]":
            done = True
            continue
        try:
            obj = json.loads(line[6:])
        except ValueError:
            continue
        if "error" in obj:
            err = obj["error"]
        for ch in obj.get("choices", []):
            text.append((ch.get("delta") or {}).get("content") or "")
            finish = ch.get("finish_reason") or finish
    return "".join(text), finish, done, err


def _mk_router(ports, ckpt_interval=2, **kw):
    state = router_mod.RouterState(
        [router_mod.Replica("127.0.0.1", p) for p in ports],
        probe_interval_s=60.0, ckpt_interval=ckpt_interval, **kw)
    state.probe_once()
    srv = router_mod.create_router_server(state, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return state, srv, srv.server_address[1]


def _resumes(state):
    return {o: state._m_resumes.value(outcome=o) for o in OUTCOMES
            if state._m_resumes.value(outcome=o)}


# ---------------------------------------------------------------------------
# checkpoint frames on the direct replica surface
# ---------------------------------------------------------------------------

def test_ckpt_frames_opt_in_and_resumable(pair):
    """No header -> no control frames. With the header, frames arrive at
    the requested cadence with increasing splice offsets, each decoding
    to a v-headered snapshot carrying the resume block — and stripping
    them leaves the visible stream's content untouched."""
    st, _, data = _post(pair[0], "/v1/chat/completions", _chat())
    assert st == 200 and b"dllama-ckpt" not in data

    st, _, data = _post(pair[0], "/v1/chat/completions", _chat(),
                        headers={"X-Dllama-Ckpt": "2"})
    assert st == 200
    visible, frames = _split_stream(data)
    assert len(frames) >= 3
    assert b"dllama-ckpt" not in visible and visible.endswith(
        b"data: [DONE]\n\n")
    offsets = [off for off, _ in frames]
    assert offsets == sorted(offsets) and offsets[0] > 0
    for off, payload in frames:
        snap = kv_transfer.decode_snapshot(payload)
        resume = snap["extra"]["resume"]
        assert resume["bytes"] == off
        for key in ("base", "utf8", "prev", "n_generated", "request_id"):
            assert key in resume, key
    # content must match the plain stream's (frame boundaries may differ:
    # a ckpt'd stream always takes the batched path)
    plain_text = _parts(_post(pair[0], "/v1/chat/completions",
                              _chat())[2])[0]
    assert _parts(data)[0] == plain_text


@pytest.mark.parametrize("which", ["first", "middle", "last"])
def test_direct_resume_splices_byte_identically(pair, which):
    """THE tentpole invariant, at its sharpest: for a checkpoint taken
    at splice offset B, POSTing the payload to a sibling's /v1/kv/resume
    returns raw bytes EQUAL to the original stream's visible bytes from
    B on — same token bytes, same frame boundaries, same terminal chunk,
    same [DONE]. Covers death exactly on a checkpoint boundary and (via
    "last") zero tokens decoded since the checkpoint."""
    st, _, data = _post(pair[0], "/v1/chat/completions", _chat(),
                        headers={"X-Dllama-Ckpt": "2"})
    assert st == 200
    visible, frames = _split_stream(data)
    idx = {"first": 0, "middle": len(frames) // 2,
           "last": len(frames) - 1}[which]
    off, payload = frames[idx]
    st, headers, cont = _post(
        pair[1], "/v1/kv/resume", payload,
        headers={"Content-Type": kv_transfer.CONTENT_TYPE})
    assert st == 200, cont
    assert int(headers.get("X-Dllama-Resume-Offset", -1)) == off
    assert cont == visible[off:]


def test_resume_on_warm_sibling_bit_identical(pair):
    """Satellite: the sibling already served the SAME prompt (its prefix
    cache is warm) — admission and continuation must still splice
    byte-identically, not replay cached frames."""
    warm = _post(pair[1], "/v1/chat/completions", _chat())
    assert warm[0] == 200
    st, _, data = _post(pair[0], "/v1/chat/completions", _chat(),
                        headers={"X-Dllama-Ckpt": "2"})
    assert st == 200
    visible, frames = _split_stream(data)
    off, payload = frames[len(frames) // 2]
    st, headers, cont = _post(
        pair[1], "/v1/kv/resume", payload,
        headers={"Content-Type": kv_transfer.CONTENT_TYPE})
    assert st == 200 and cont == visible[off:]


def test_stop_string_session_resumes_with_scanback(pair):
    """Satellite: stop-string sessions checkpoint too (the scanback
    rides the v2 header) and the spliced continuation still honors the
    stop — closing the ROADMAP carry that pinned stop sessions to one
    replica."""
    plain = _parts(_post(pair[0], "/v1/chat/completions",
                         _chat(max_tokens=20))[2])[0]
    assert len(plain) >= 10
    # a stop the stream WILL emit, whose FIRST occurrence lands late
    # enough that a checkpoint precedes the stop hit, yet strictly
    # inside the stream (a stop completing only in the final dangling-
    # byte UTF-8 flush is a different edge than the one under test)
    stop = max((plain[i:i + 3] for i in range(len(plain) - 7)),
               key=lambda s: plain.find(s) if plain.find(s)
               <= len(plain) - 8 else -1)
    assert 4 <= plain.find(stop) <= len(plain) - 8, (plain, stop)
    st, _, data = _post(pair[0], "/v1/chat/completions",
                        _chat(max_tokens=20, stop=[stop]),
                        headers={"X-Dllama-Ckpt": "2"})
    assert st == 200
    visible, frames = _split_stream(data)
    text, finish, done, _ = _parts(data)
    assert finish == "stop" and done
    assert frames, "stop session produced no checkpoints"
    snap = kv_transfer.decode_snapshot(frames[0][1])
    assert snap["stop_state"] is not None
    assert snap["stop_state"]["stops"] == [stop]
    off, payload = frames[0]
    st, _, cont = _post(
        pair[1], "/v1/kv/resume", payload,
        headers={"Content-Type": kv_transfer.CONTENT_TYPE})
    assert st == 200 and cont == visible[off:]
    assert _parts(cont)[1] == "stop"


def test_resume_rejects_non_resumable_payload_with_reason(pair):
    """A v1 migration payload (no resume block) is a valid KV snapshot
    but NOT a resumable checkpoint: /v1/kv/resume must 422 with the
    reason, never guess a splice offset."""
    st, _, data = _post(pair[0], "/v1/chat/completions", _chat(),
                        headers={"X-Dllama-Ckpt": "2"})
    assert st == 200
    _, frames = _split_stream(data)
    snap = kv_transfer.decode_snapshot(frames[0][1])
    bare = kv_transfer.encode_snapshot(snap, snap["prompt"], mode="f32")
    st, _, body = _post(pair[1], "/v1/kv/resume", bare,
                        headers={"Content-Type": kv_transfer.CONTENT_TYPE})
    assert st == 422
    assert b"resumable" in body
    st, _, body = _post(pair[1], "/v1/kv/resume", b"garbage",
                        headers={"Content-Type": kv_transfer.CONTENT_TYPE})
    assert st == 422


# ---------------------------------------------------------------------------
# router orchestration: the happy path and the fallback matrix
# ---------------------------------------------------------------------------

def test_router_resume_after_death_content_identical(pair):
    """A replica death mid-SSE is a non-event: one client connection,
    the complete stream, outcome="ok" counted, no control-frame leak."""
    state, srv, port = _mk_router(pair)
    try:
        ref = _post(port, "/v1/chat/completions", _chat())
        assert ref[0] == 200
        ref_text, ref_finish, ref_done, _ = _parts(ref[2])
        assert ref_done and ref_text
        faults.install("stream:raise:after=4,times=1")
        st, _, data = _post(port, "/v1/chat/completions", _chat())
        faults.clear()
        assert st == 200 and b"dllama-ckpt" not in data
        text, finish, done, err = _parts(data)
        assert err is None and done
        assert (text, finish) == (ref_text, ref_finish)
        assert _resumes(state) == {"ok": 1.0}
        assert len(state.ckpt_store) == 0  # popped at stream end
    finally:
        srv.shutdown()


def test_router_death_between_ckpts_discards_regenerated_prefix(pair):
    """Death BETWEEN checkpoints: the resumed stream regenerates bytes
    the client already holds; the router must discard exactly that
    prefix (no duplicate, no gap). Interval 4 with chunk 2 makes every
    other burst un-checkpointed."""
    state, srv, port = _mk_router(pair, ckpt_interval=4)
    try:
        ref_text = _parts(_post(port, "/v1/chat/completions",
                                _chat())[2])[0]
        faults.install("stream:raise:after=4,times=1")
        st, _, data = _post(port, "/v1/chat/completions", _chat())
        faults.clear()
        text, _, done, err = _parts(data)
        assert st == 200 and done and err is None
        assert text == ref_text
        assert _resumes(state) == {"ok": 1.0}
    finally:
        srv.shutdown()


def test_router_exhausted_emits_typed_error_event(pair):
    """Satellite bugfix pin: when resume is exhausted (second death),
    the client gets a typed SSE error event AND a [DONE] — a torn
    stream is distinguishable from a complete one without timeout
    heuristics."""
    state, srv, port = _mk_router(pair)
    try:
        faults.install("stream:raise:after=4,times=2")
        st, _, data = _post(port, "/v1/chat/completions", _chat())
        faults.clear()
        assert st == 200
        _, _, done, err = _parts(data)
        assert done, "no terminal [DONE] after exhaustion"
        assert err is not None and err["type"] == "upstream_error"
        assert "died again" in err["message"]
        assert data.rstrip().endswith(b"data: [DONE]")
        got = _resumes(state)
        assert got.get("ok") == 1.0 and got.get("exhausted") == 1.0
    finally:
        srv.shutdown()


@pytest.mark.parametrize("name,plan,outcome", [
    ("injected", "stream:raise:after=4,times=1;resume:raise:times=1",
     "injected"),
    ("no_ckpt", "stream:raise:after=4,times=1;ckpt_write:raise",
     "no_ckpt"),
    ("admit_failed", "stream:raise:after=4,times=1;kv_import:raise",
     "admit_failed"),
])
def test_router_fallback_matrix_clean_termination(pair, name, plan,
                                                  outcome):
    """Every injectable fallback row: HTTP 200, a typed error event, a
    [DONE], and exactly one increment of the matching outcome."""
    state, srv, port = _mk_router(pair)
    try:
        faults.install(plan)
        st, _, data = _post(port, "/v1/chat/completions", _chat())
        faults.clear()
        assert st == 200, name
        _, _, done, err = _parts(data)
        assert done and err is not None, (name, data[-300:])
        assert _resumes(state) == {outcome: 1.0}
    finally:
        srv.shutdown()


def test_router_stale_checkpoint_refused(pair):
    """A checkpoint claiming MORE bytes than the client holds would
    splice a gap — the router must refuse (stale_ckpt) and terminate
    cleanly rather than corrupt the stream."""
    state, srv, port = _mk_router(pair)
    real_put = state.ckpt_store.put
    state.ckpt_store.put = (
        lambda rid, payload, offset, replica:
        real_put(rid, payload, offset + 10**9, replica))
    try:
        faults.install("stream:raise:after=4,times=1")
        st, _, data = _post(port, "/v1/chat/completions", _chat())
        faults.clear()
        _, _, done, err = _parts(data)
        assert st == 200 and done and err is not None
        assert _resumes(state) == {"stale_ckpt": 1.0}
    finally:
        srv.shutdown()


def test_router_no_replica_when_fleet_is_one(pair):
    state, srv, port = _mk_router(pair[:1])
    try:
        faults.install("stream:raise:after=4,times=1")
        st, _, data = _post(port, "/v1/chat/completions", _chat())
        faults.clear()
        _, _, done, err = _parts(data)
        assert st == 200 and done and err is not None
        assert _resumes(state) == {"no_replica": 1.0}
    finally:
        srv.shutdown()


def test_router_ckpt_disabled_passthrough(pair):
    """--ckpt-interval 0 keeps the old passthrough relay: no header sent
    upstream, no frames, no resume orchestration."""
    state, srv, port = _mk_router(pair, ckpt_interval=0)
    try:
        st, _, data = _post(port, "/v1/chat/completions", _chat())
        assert st == 200 and b"dllama-ckpt" not in data
        assert _parts(data)[2]  # [DONE]
        assert _resumes(state) == {}
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# the bounded store and the splice plumbing
# ---------------------------------------------------------------------------

def test_checkpoint_store_lru_bounds():
    store = router_mod.CheckpointStore(capacity=3)
    for i in range(4):
        store.put(f"r{i}", b"p%d" % i, i * 10, "rep")
    assert len(store) == 3 and store.get("r0") is None
    entry = store.get("r1")  # touch: r1 becomes most-recent
    assert entry["payload"] == b"p1" and entry["offset"] == 10
    store.put("r4", b"p4", 40, "rep")
    assert store.get("r2") is None and store.get("r1") is not None
    store.put("r1", b"p1b", 99, "rep")  # same rid overwrites, no growth
    assert len(store) == 3 and store.get("r1")["offset"] == 99
    store.pop("r1")
    assert store.get("r1") is None and len(store) == 2
    store.pop("missing")  # pop is idempotent


def test_utf8_decoder_state_survives_hex_round_trip():
    """The checkpoint carries the incremental UTF-8 decoder state as
    (hex, flag) — restoring it mid-multi-byte-character must continue
    the character, not emit a replacement char (the splice-through-a-
    UTF-8-token edge)."""
    one = codecs.getincrementaldecoder("utf-8")("replace")
    whole = one.decode("héllo".encode("utf-8"))
    src = codecs.getincrementaldecoder("utf-8")("replace")
    first = src.decode("héllo".encode("utf-8")[:2])  # cut mid é
    buf, flag = src.getstate()
    dst = codecs.getincrementaldecoder("utf-8")("replace")
    dst.setstate((bytes.fromhex(buf.hex()), int(flag)))
    assert first + dst.decode("héllo".encode("utf-8")[2:]) == whole
