"""Pipeline-parallel tests: the GPipe schedule over a pp mesh axis must be
numerically identical to the plain layer scan (forward AND gradients), for
every stage count that divides the layer stack — the sharding-invariance
pattern of the reference's transformer-test applied to the pipeline axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models import llama
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.parallel.pipeline import pipeline_forward_train

from tests.test_llama_forward import tiny_cfg


def _setup(n_layers=4, B=4, T=8):
    cfg = tiny_cfg(n_layers=n_layers, seq_len=32)
    params = jax.tree.map(
        lambda x: jnp.asarray(x, jnp.float32), llama.random_params(cfg, seed=11)
    )
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, cfg.vocab_size, (B, T)), jnp.int32
    )
    return cfg, params, tokens


@pytest.mark.parametrize("pp,microbatches", [(2, 2), (2, 4), (4, 4), (1, 2)])
def test_pipeline_matches_dense_forward(pp, microbatches):
    cfg, params, tokens = _setup()
    dense = llama.forward_train(cfg, params, tokens)
    mesh = make_mesh({"pp": pp})
    piped = jax.jit(
        lambda p, t: pipeline_forward_train(
            cfg, p, t, mesh, n_microbatches=microbatches
        )
    )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(piped), np.asarray(dense), rtol=2e-5, atol=2e-5
    )


def test_pipeline_gradients_match_dense():
    cfg, params, tokens = _setup()
    mesh = make_mesh({"pp": 4})

    def dense_loss(p):
        return (llama.forward_train(cfg, p, tokens) ** 2).mean()

    def piped_loss(p):
        return (
            pipeline_forward_train(cfg, p, tokens, mesh, n_microbatches=4) ** 2
        ).mean()

    g_dense = jax.grad(dense_loss)(params)
    g_piped = jax.jit(jax.grad(piped_loss))(params)
    diffs = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - jax.device_get(b)))), g_dense, g_piped
    )
    assert max(jax.tree.leaves(diffs)) < 2e-4, diffs


def test_pipeline_remat_matches():
    cfg, params, tokens = _setup()
    mesh = make_mesh({"pp": 2})
    a = jax.jit(
        lambda p, t: pipeline_forward_train(cfg, p, t, mesh, n_microbatches=2)
    )(params, tokens)
    b = jax.jit(
        lambda p, t: pipeline_forward_train(
            cfg, p, t, mesh, n_microbatches=2, remat=True
        )
    )(params, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def test_pipeline_rejects_bad_divisibility():
    cfg, params, tokens = _setup(n_layers=4, B=4)
    mesh = make_mesh({"pp": 4})
    with pytest.raises(ValueError):
        pipeline_forward_train(cfg, params, tokens[:3], mesh, n_microbatches=2)
    cfg3, params3, tokens3 = _setup(n_layers=3)
    with pytest.raises(ValueError):
        pipeline_forward_train(cfg3, params3, tokens3, mesh, n_microbatches=2)
