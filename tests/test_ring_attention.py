"""Ring attention (sequence/context parallelism) — invariance vs dense
attention on the faked 8-device CPU mesh.

This is the sharding-invariance pattern of the reference's transformer-test
(`/root/reference/src/transformer-test.cpp:6-84` — sliced must equal 1-slice)
applied to the sequence axis the reference never distributes (SURVEY.md §2.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.ops.ring_attention import ring_self_attention
from dllama_tpu.parallel.mesh import make_mesh


def dense_causal_gqa(q, k, v):
    """Reference: plain masked softmax attention, f32."""
    B, T, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    qf = q.astype(jnp.float32).reshape(B, T, Hkv, G, D)
    scores = jnp.einsum("btkgh,bskh->bkgts", qf, k.astype(jnp.float32))
    scores = scores / jnp.sqrt(jnp.float32(D))
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask[None, None, None], scores, -1e30)
    att = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskh->btkgh", att, v.astype(jnp.float32))
    return out.reshape(B, T, Hq, D)


def _qkv(B, T, Hq, Hkv, D, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, T, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("n_sp", [2, 4, 8])
def test_ring_equals_dense_causal(n_sp):
    B, T, Hq, Hkv, D = 2, 64, 8, 4, 16
    q, k, v = _qkv(B, T, Hq, Hkv, D, seed=1)
    mesh = make_mesh({"sp": n_sp})
    out = ring_self_attention(q, k, v, mesh)
    ref = dense_causal_gqa(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_non_causal():
    B, T, Hq, Hkv, D = 1, 32, 4, 4, 8
    q, k, v = _qkv(B, T, Hq, Hkv, D, seed=2)
    mesh = make_mesh({"sp": 4})
    out = ring_self_attention(q, k, v, mesh, causal=False)
    qf = q.astype(jnp.float32)
    scores = jnp.einsum("bthd,bshd->bhts", qf, k) / jnp.sqrt(jnp.float32(D))
    att = jax.nn.softmax(scores, axis=-1)
    ref = jnp.einsum("bhts,bshd->bthd", att, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_with_extra_mesh_axes():
    """sp ring must compose with dp/tp axes left automatic."""
    B, T, Hq, Hkv, D = 2, 32, 4, 2, 8
    q, k, v = _qkv(B, T, Hq, Hkv, D, seed=3)
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    out = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh))(q, k, v)
    ref = dense_causal_gqa(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_gradients_flow():
    """Training shards sequence too: the ring must be reverse-differentiable
    and match dense-attention gradients."""
    B, T, Hq, Hkv, D = 1, 32, 2, 2, 8
    q, k, v = _qkv(B, T, Hq, Hkv, D, seed=4)
    mesh = make_mesh({"sp": 4})

    def loss_ring(q, k, v):
        return (ring_self_attention(q, k, v, mesh) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_causal_gqa(q, k, v) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), rtol=1e-4, atol=1e-4)
