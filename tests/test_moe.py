"""MoE (Grok-1 / Mixtral) tests: golden forward vs serial numpy oracle
(the grok1-tasks-test pattern, `/root/reference/src/grok1-tasks-test.cpp`),
routing properties, TP sharding invariance, end-to-end .m load."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models import llama, moe
from dllama_tpu.models.config import GROK_EMBEDDING_SCALE, GROK_LOGIT_SCALE, ModelConfig
from dllama_tpu.parallel.mesh import tp_mesh
from dllama_tpu.parallel.sharding import shard_params
from dllama_tpu.runtime.generate import Engine
from dllama_tpu.runtime.sampler import SamplerConfig

from tests import reference_impl
from tests.test_llama_forward import tiny_cfg


def grok_cfg(**kw):
    base = dict(
        arch="grok1",
        n_experts=4,
        n_active_experts=2,
        hidden_act="gelu",
        rope_style="half",
        embedding_scale=GROK_EMBEDDING_SCALE,
        logit_scale=GROK_LOGIT_SCALE,
        post_norms=True,
    )
    base.update(kw)
    return tiny_cfg(**base)


def mixtral_cfg(**kw):
    base = dict(
        arch="mixtral", n_experts=4, n_active_experts=2, hidden_act="silu", rope_style="half"
    )
    base.update(kw)
    return tiny_cfg(**base)


@pytest.mark.parametrize("make_cfg", [grok_cfg, mixtral_cfg], ids=["grok1", "mixtral"])
def test_moe_forward_matches_numpy_oracle(make_cfg):
    cfg = make_cfg()
    params = llama.random_params(cfg, seed=8)
    tokens = np.array([5, 99, 3, 42], dtype=np.int32)
    logits, _ = llama.forward(
        cfg,
        jax.tree.map(jnp.asarray, params),
        llama.rope_tables(cfg),
        jnp.asarray(tokens),
        llama.init_cache(cfg),
        0,
    )
    want, _ = reference_impl.forward_tokens(cfg, params, tokens)
    np.testing.assert_allclose(np.asarray(logits), want, atol=3e-4, rtol=3e-3)


def test_route_properties():
    cfg = mixtral_cfg()
    rng = np.random.default_rng(0)
    router = jnp.asarray(rng.standard_normal((cfg.dim, cfg.n_experts)), jnp.float32)
    xb = jnp.asarray(rng.standard_normal((5, cfg.dim)), jnp.float32)
    combine = np.asarray(moe.route(cfg, router, xb))
    assert combine.shape == (5, cfg.n_experts)
    # exactly k nonzero weights per token, summing to 1
    nz = (combine > 0).sum(axis=-1)
    np.testing.assert_array_equal(nz, cfg.n_active_experts)
    np.testing.assert_allclose(combine.sum(axis=-1), 1.0, rtol=1e-5)


def test_moe_generation_and_continuation():
    cfg = mixtral_cfg()
    eng = Engine(cfg, llama.random_params(cfg, seed=3), SamplerConfig(temperature=0.0))
    out = [t for t, _ in eng.generate([1, 5], steps=5)]
    assert len(out) == 5
    fused, _, _ = Engine(
        cfg, llama.random_params(cfg, seed=3), SamplerConfig(temperature=0.0)
    ).generate_fused([1, 5], steps=5)
    assert fused == out


@pytest.mark.parametrize("make_cfg", [grok_cfg, mixtral_cfg], ids=["grok1", "mixtral"])
def test_moe_forward_invariant_under_tp(make_cfg):
    cfg = make_cfg(n_heads=8, n_kv_heads=8, dim=128, kv_dim=128, head_size=16, hidden_dim=96)
    params = llama.random_params(cfg, seed=13)
    rope = llama.rope_tables(cfg)
    tokens = jnp.asarray([3, 77, 12], jnp.int32)
    base, _ = llama.forward(
        cfg, jax.tree.map(jnp.asarray, params), rope, tokens, llama.init_cache(cfg), 0
    )
    sharded = shard_params(params, tp_mesh(4), cfg)
    got, _ = llama.forward(cfg, sharded, rope, tokens, llama.init_cache(cfg), 0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base), atol=2e-5, rtol=1e-4)


def test_moe_loads_from_m_file(tmp_path):
    """Write a grok-1 arch .m file, load, decode — full path."""
    from dllama_tpu.formats.spec import ArchType, HiddenAct, ModelSpec
    from dllama_tpu.formats.weights import WeightFileReader, tensor_plan, write_model
    from dllama_tpu.quants import blocks

    spec = ModelSpec(
        arch=ArchType.GROK1,
        dim=64,
        hidden_dim=96,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        vocab_size=128,
        seq_len=24,
        n_experts=4,
        n_active_experts=2,
        hidden_act=HiddenAct.GELU,
        weights_float_type=blocks.Q80,
    )
    rng = np.random.default_rng(0)
    tensors = {
        e.name: (rng.standard_normal(e.d * e.n) * 0.02).astype(np.float32)
        for e in tensor_plan(spec)
    }
    path = str(tmp_path / "grok.m")
    write_model(path, spec, tensors)

    with WeightFileReader(path) as reader:
        cfg = ModelConfig.from_spec(reader.spec)
        assert cfg.post_norms and cfg.embedding_scale == GROK_EMBEDDING_SCALE
        params = llama.params_from_reader(reader, cfg)
    assert params["layers"]["moe_up"].shape == (2, 4, 64, 96)
    assert params["layers"]["rms_ffn2"].shape == (2, 64)
    eng = Engine(cfg, params, SamplerConfig(temperature=0.0))
    out = [t for t, _ in eng.generate([1, 2], steps=4)]
    assert len(out) == 4


def test_device_random_quant_params_moe_decode():
    """The bench's on-device random q40 builder covers MoE (BENCH_MODEL=moe):
    [L, E, ...] expert plane stacks + dense router must drive the
    selected-experts quantized decode path end to end."""
    cfg = mixtral_cfg(hidden_dim=128)
    params = llama.device_random_quant_params(cfg, kind="q40", seed=0)
    qt = params["layers"]["moe_up"]
    assert qt.w.shape[:2] == (cfg.n_layers, cfg.n_experts)
    assert params["layers"]["moe_router"].shape == (
        cfg.n_layers, cfg.dim, cfg.n_experts)
    eng = Engine(cfg, params, SamplerConfig(temperature=0.0))
    toks, _, _ = eng.generate_fused([1, 2], steps=3)
    assert len(toks) == 3 and all(0 <= t < cfg.vocab_size for t in toks)
