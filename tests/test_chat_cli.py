"""Chat mode end-to-end over a subprocess (stdin-driven), including greedy
spec-decode equivalence — run_chat had no runtime coverage at all."""

import os
import subprocess
import sys

import numpy as np
import pytest

from dllama_tpu.formats.spec import ArchType, ModelSpec
from dllama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer
from dllama_tpu.formats.weights import tensor_plan, write_model
from dllama_tpu.quants import blocks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def demo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("chat_demo")
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=300, seq_len=96,
                     weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    write_model(str(d / "m.m"), spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(np.float32)
                 for e in tensor_plan(spec)})
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + [b"hi"] * 41
    write_tokenizer(str(d / "t.t"),
                    TokenizerData(vocab=vocab, scores=[0.0] * 300, bos_id=1, eos_id=2))
    return str(d / "m.m"), str(d / "t.t")


def _normalize(out: str) -> str:
    """Blank out wall-clock-dependent text (load-time line) so transcript
    equality tests don't flake on timing jitter between two runs."""
    import re
    return re.sub(r"loaded weights in \d+\.\d+s", "loaded weights in Xs", out)


def run_chat(demo_files, *extra, turns=("hi", "hi again")):
    model, tok = demo_files
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("JAX_PLATFORM_NAME", None)
    # CPU child must not register the axon TPU plugin: sitecustomize's
    # register() blocks at interpreter start while another process holds the
    # (single-session) tunnel, even under JAX_PLATFORMS=cpu
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.cli", "chat", "--model", model,
         "--tokenizer", tok, "--steps", "6", "--temperature", "0", "--tp", "1",
         "--system-prompt", "", "--chat-template", "llama2", *extra],
        input="\n".join(turns) + "\n", capture_output=True, text=True,
        env=env, cwd=REPO, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


def test_chat_two_turns(demo_files):
    out = run_chat(demo_files)
    assert out.count("🤖 Assistant:") == 2


def test_chat_spec_matches_plain(demo_files):
    """Greedy chat transcripts must be identical with and without
    speculative drafting (exactness across multi-turn sessions + history)."""
    plain = run_chat(demo_files)
    spec = run_chat(demo_files, "--spec-draft", "4")
    assert _normalize(plain) == _normalize(spec)


def test_chat_spec_sampled_matches_plain(demo_files):
    """Sampled chat (same --seed) must transcript-match with and without
    speculative drafting: the spec path replays the same engine key chain.
    (argparse is last-wins, so the extra flags override run_chat's defaults.)"""
    sampled = ("--temperature", "0.8", "--seed", "42")
    assert _normalize(run_chat(demo_files, *sampled)) == _normalize(
        run_chat(demo_files, *sampled, "--spec-draft", "4"))
