"""Golden forward tests: vectorized JAX model vs the independent serial numpy
oracle (the llama2-tasks-test pattern, `/root/reference/src/llama2-tasks-test.cpp`,
but with a computed rather than hard-coded golden)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models.config import ModelConfig
from dllama_tpu.models import llama

from tests import reference_impl


def tiny_cfg(**kw):
    defaults = dict(
        arch="llama",
        dim=64,
        hidden_dim=96,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        vocab_size=128,
        seq_len=24,
        head_size=16,
        kv_dim=32,
        hidden_act="silu",
        rope_theta=10000.0,
        rope_style="interleaved",
    )
    defaults.update(kw)
    return ModelConfig(**defaults)


@pytest.mark.parametrize("rope_style", ["interleaved", "half"])
@pytest.mark.parametrize("hidden_act", ["silu", "gelu"])
def test_forward_matches_numpy_oracle(rope_style, hidden_act):
    cfg = tiny_cfg(rope_style=rope_style, hidden_act=hidden_act)
    params = llama.random_params(cfg, seed=3)
    rope = llama.rope_tables(cfg)
    tokens = np.array([5, 99, 3, 42, 17], dtype=np.int32)

    logits, _ = llama.forward(
        cfg, jax.tree.map(jnp.asarray, params), rope, jnp.asarray(tokens), llama.init_cache(cfg), 0
    )
    want, _ = reference_impl.forward_tokens(cfg, params, tokens)
    np.testing.assert_allclose(np.asarray(logits), want, atol=2e-4, rtol=2e-3)


def test_decode_equals_prefill():
    """Feeding tokens one at a time through the cache must equal batched prefill."""
    cfg = tiny_cfg()
    params = jax.tree.map(jnp.asarray, llama.random_params(cfg, seed=11))
    rope = llama.rope_tables(cfg)
    tokens = np.array([1, 7, 13, 2, 9, 64], dtype=np.int32)

    batched, _ = llama.forward(cfg, params, rope, jnp.asarray(tokens), llama.init_cache(cfg), 0)

    cache = llama.init_cache(cfg)
    step = jax.jit(lambda tok, cache, pos: llama.forward(cfg, params, rope, tok, cache, pos))
    per_tok = []
    for i, t in enumerate(tokens):
        logits, cache = step(jnp.asarray([t], jnp.int32), cache, jnp.int32(i))
        per_tok.append(np.asarray(logits[0]))
    np.testing.assert_allclose(np.stack(per_tok), np.asarray(batched), atol=2e-4, rtol=2e-3)


def test_continuation_from_cache():
    """Prefill a prompt, then decode — positions and mask must line up."""
    cfg = tiny_cfg()
    params = jax.tree.map(jnp.asarray, llama.random_params(cfg, seed=5))
    rope = llama.rope_tables(cfg)
    prompt = np.array([3, 1, 4, 1, 5], dtype=np.int32)
    nxt = np.array([9], dtype=np.int32)

    _, cache = llama.forward(cfg, params, rope, jnp.asarray(prompt), llama.init_cache(cfg), 0)
    logits, _ = llama.forward(cfg, params, rope, jnp.asarray(nxt), cache, jnp.int32(len(prompt)))

    full, _ = llama.forward(
        cfg, params, rope, jnp.asarray(np.concatenate([prompt, nxt])), llama.init_cache(cfg), 0
    )
    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(full[-1]), atol=1e-4, rtol=1e-3)


def test_forward_is_jittable_no_recompile():
    cfg = tiny_cfg()
    params = jax.tree.map(jnp.asarray, llama.random_params(cfg, seed=0))
    rope = llama.rope_tables(cfg)
    step = jax.jit(lambda tok, cache, pos: llama.forward(cfg, params, rope, tok, cache, pos))
    cache = llama.init_cache(cfg)
    tok = jnp.asarray([4], jnp.int32)
    _, cache = step(tok, cache, jnp.int32(0))
    compiles_before = step._cache_size()
    _, cache = step(jnp.asarray([9], jnp.int32), cache, jnp.int32(1))
    assert step._cache_size() == compiles_before  # pos is traced, not static


def test_model_loads_from_m_file(tmp_path):
    """End-to-end: write a .m file, load params, run forward."""
    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.weights import WeightFileReader, tensor_plan, write_model
    from dllama_tpu.quants import blocks

    spec = ModelSpec(
        arch=ArchType.LLAMA,
        dim=64,
        hidden_dim=96,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        vocab_size=128,
        seq_len=24,
        weights_float_type=blocks.Q80,
    )
    rng = np.random.default_rng(0)
    tensors = {
        e.name: (rng.standard_normal(e.d * e.n) * 0.02).astype(np.float32)
        for e in tensor_plan(spec)
    }
    path = str(tmp_path / "m.m")
    write_model(path, spec, tensors)

    with WeightFileReader(path) as reader:
        cfg = ModelConfig.from_spec(reader.spec)
        params = llama.params_from_reader(reader, cfg)
    assert params["layers"]["wq"].shape == (2, 64, 64)
    assert params["layers"]["w2"].shape == (2, 96, 64)
    logits, _ = llama.forward(
        cfg,
        jax.tree.map(jnp.asarray, params),
        llama.rope_tables(cfg),
        jnp.asarray([1, 2, 3], jnp.int32),
        llama.init_cache(cfg),
        0,
    )
    assert logits.shape == (3, 128)
    assert np.all(np.isfinite(np.asarray(logits)))
