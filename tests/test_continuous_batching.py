"""Continuous batching (Engine.batch_session + the rolling-admission server).

The slot-pool session's contract is that membership in the pool is
invisible in the tokens: a row admitted mid-flight into a half-busy pool —
or into a slab a previous request just vacated — emits EXACTLY the stream
of a solo ``generate()`` with the same SamplerConfig, and a live row nets
at least one token per chunk, so staggered arrivals can never starve or
deadlock. These tests pin all of that, engine-level and over real HTTP.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from dllama_tpu.models import llama
from dllama_tpu.models.config import ModelConfig
from dllama_tpu.runtime.generate import Engine
from dllama_tpu.runtime.sampler import SamplerConfig

CFG = ModelConfig(
    arch="llama", dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
    vocab_size=96, seq_len=64, head_size=16, kv_dim=32, dtype="float32",
)

PROMPTS = [[5, 9, 3], [7], [1, 2, 3, 4, 5, 6, 11]]  # mixed lengths incl. 1


def _solo(params, prompt, steps, sampler=None, cfg=CFG):
    eng = Engine(cfg, params, SamplerConfig(temperature=0.0))
    return [t for t, _ in eng.generate(list(prompt), steps=steps,
                                       sampler=sampler)]


def _drain(sess, slots):
    """Step until every slot in ``slots`` is done; return {slot: tokens}."""
    out = {b: [] for b in slots}
    while any(not sess.is_done(b) for b in slots):
        for b, burst in sess.step_chunk().items():
            if b in out:
                out[b].extend(burst)
    return out


def test_mid_flight_admit_bit_identical_to_solo():
    """The tentpole invariant: a row admitted while the pool is mid-decode
    (sampled or greedy, any slot) emits exactly its solo stream — the
    resident batch cache, the pinned free rows, and the other rows'
    key-chain splits must all be invisible to it."""
    params = llama.random_params(CFG, seed=0, dtype=np.float32)
    samplers = [
        SamplerConfig(temperature=0.9, topp=0.95, seed=7),
        SamplerConfig(temperature=0.0, seed=1),      # greedy row in the mix
        SamplerConfig(temperature=1.3, topp=0.8, seed=42),
    ]
    want = [_solo(params, p, 12, s) for p, s in zip(PROMPTS, samplers)]

    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    sess = eng.batch_session(max_batch=3, chunk=4)
    got = {}
    s0 = sess.admit(PROMPTS[0], steps=12, sampler=samplers[0])
    got[s0] = []
    for b, burst in sess.step_chunk().items():  # row 0 is already 4 deep...
        got[b].extend(burst)
    s1 = sess.admit(PROMPTS[1], steps=12, sampler=samplers[1])  # ...join now
    got[s1] = []
    for b, burst in sess.step_chunk().items():
        got[b].extend(burst)
    s2 = sess.admit(PROMPTS[2], steps=12, sampler=samplers[2])  # 8 deep
    got[s2] = []
    for b, tokens in _drain(sess, [s0, s1, s2]).items():
        got[b].extend(tokens)
    sess.close()
    assert [got[s0], got[s1], got[s2]] == want


def test_released_slot_reuse_no_contamination():
    """A slab vacated by release() is reused WITHOUT clearing (admit
    overwrites the prefix; positions past the new row's pos are masked) —
    the successor must still match its solo stream bit for bit."""
    params = llama.random_params(CFG, seed=3, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    sess = eng.batch_session(max_batch=2, chunk=4)

    first = sess.admit([9, 2, 4, 8, 1, 3], steps=8,
                       sampler=SamplerConfig(temperature=1.1, seed=5))
    _drain(sess, [first])
    sess.release(first)
    assert sess.free_slots == [0, 1]

    # the 1-token successor lands in the dirtiest possible slab state:
    # its pos-0 write leaves every other position holding the first
    # request's stale KV, all of which must stay masked out
    reuse = sess.admit([7], steps=10,
                       sampler=SamplerConfig(temperature=0.8, seed=11))
    assert reuse == first  # lowest free slot: genuinely the same slab
    got = _drain(sess, [reuse])[reuse]
    sess.close()
    assert got == _solo(params, [7], 10, SamplerConfig(temperature=0.8, seed=11))


def test_stop_token_truncates_inclusively_and_frees_early():
    params = llama.random_params(CFG, seed=5, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    full = _solo(params, PROMPTS[0], 16)
    # first token that does not appear earlier in the stream: stopping on it
    # pins exactly where the solo stream first emits it
    k = next(i for i, t in enumerate(full) if t not in full[:i])
    sess = eng.batch_session(max_batch=2, chunk=4)
    s0 = sess.admit(PROMPTS[0], steps=16, stop_tokens=(full[k],))
    got = _drain(sess, [s0])[s0]
    assert got == full[: k + 1]  # stop token emitted, nothing after
    sess.release(s0)
    sess.close()


def test_budget_and_accounting():
    """Bookkeeping the scheduler leans on: per-chunk bursts are never empty
    for a live row, sum to the budget, and done rows leave step_chunk()."""
    params = llama.random_params(CFG, seed=6, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    sess = eng.batch_session(max_batch=2, chunk=4)
    short = sess.admit(PROMPTS[0], steps=6)   # done after chunk 2
    long = sess.admit(PROMPTS[2], steps=11)
    bursts = {short: [], long: []}
    while not (sess.is_done(short) and sess.is_done(long)):
        fresh = sess.step_chunk()
        assert all(burst for burst in fresh.values())  # live rows always net
        for b, burst in fresh.items():
            bursts[b].append(len(burst))
    assert sum(bursts[short]) == 6 and sum(bursts[long]) == 11
    assert len(bursts[short]) == 2  # 4 + 2, absent from later chunks
    assert sess.num_live == 0 and sess.is_done(short) and sess.is_done(long)
    sess.release(short)
    assert sess.free_slots == [0]
    with pytest.raises(ValueError):
        sess.is_done(short)  # released slot is no longer queryable
    sess.close()


def test_admit_validation():
    params = llama.random_params(CFG, seed=0, dtype=np.float32)
    eng = Engine(CFG, params, SamplerConfig(temperature=0.0))
    sess = eng.batch_session(max_batch=1, chunk=2)
    with pytest.raises(ValueError):
        sess.admit([], steps=4)  # empty prompt
    sess.admit([5], steps=4)
    with pytest.raises(RuntimeError):
        sess.admit([7], steps=4)  # pool full
    sess.close()
    with pytest.raises(RuntimeError):
        sess.admit([5], steps=4)  # closed session


# ---------------------------------------------------------------------------
# Server-level: staggered arrivals through the rolling-admission scheduler
# ---------------------------------------------------------------------------

def _request(port, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions", body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_staggered_arrivals_drain_without_deadlock():
    """More requests than slots, arriving spread across several decode
    chunks: the scheduler must admit them into freed slots mid-flight and
    answer every one (timeout-guarded), with the same tokens a
    batching-disabled server returns."""
    from dllama_tpu.formats.tokenizer_file import TokenizerData
    from dllama_tpu.serving.api_server import ServerState, create_server
    from dllama_tpu.tokenizer.bpe import Tokenizer

    from tests.test_llama_forward import tiny_cfg

    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [b"<0x%02X>" % b for b in range(256)]
    vocab += [b" ", b"e", b"t", b"he", b" the", b"hello", b" world"]
    scores = [0.0] * 259 + [-1.0, -2.0, -2.0, -1.5, -1.2, -1.1, -1.1]
    tok = Tokenizer(TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2))
    cfg = tiny_cfg(vocab_size=tok.vocab_size, seq_len=512, dim=32, kv_dim=16,
                   head_size=8, hidden_dim=64)
    params = llama.random_params(cfg, seed=13)

    def run_server(window_ms):
        engine = Engine(cfg, params, SamplerConfig(temperature=0.0, seed=1))
        state = ServerState(engine, tok, cfg, model_name="tiny-test",
                            template="llama3", batch_window_ms=window_ms,
                            batch_max=2, batch_chunk=2)
        srv = create_server(state, host="127.0.0.1", port=0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, srv.server_address[1]

    prompts = ["hello world", "the the cat", "world hello the",
               "hello the world", "t e t e"]

    def ask_all(port, stagger_s=0.0):
        replies = [None] * len(prompts)

        def one(i):
            if stagger_s:
                time.sleep(i * stagger_s)
            _, d = _request(port, {
                "model": "tiny-test", "temperature": 0.0,
                "max_tokens": 4 + 4 * (i % 3),  # mixed budgets
                "messages": [{"role": "user", "content": prompts[i]}],
            })
            replies[i] = json.loads(d)["choices"][0]["message"]["content"]

        threads = [threading.Thread(target=one, args=(i,), daemon=True)
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 120
        for t in threads:
            t.join(timeout=max(1.0, deadline - time.monotonic()))
        assert not any(t.is_alive() for t in threads), \
            "staggered requests deadlocked"
        return replies

    srv_plain, port_plain = run_server(0)
    srv_batch, port_batch = run_server(40.0)
    try:
        # warm compile caches so arrival timing isn't swamped by tracing
        _request(port_batch, {"model": "tiny-test", "temperature": 0.0,
                              "max_tokens": 2,
                              "messages": [{"role": "user", "content": "hi"}]})
        want = ask_all(port_plain)
        got = ask_all(port_batch, stagger_s=0.05)
        assert None not in got
        assert got == want
    finally:
        srv_plain.shutdown()
        srv_batch.shutdown()
