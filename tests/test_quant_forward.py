"""End-to-end forward/decode with block-quantized weights (the fused-kernel
path) against the dense forward — the integration analogue of the reference's
matmulQ40vQ80-vs-F32 check (`/root/reference/src/funcs-test.cpp:18-60`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats.spec import ModelSpec
from dllama_tpu.formats.weights import ModelWriter, WeightFileReader
from dllama_tpu.models import llama
from dllama_tpu.models.config import ModelConfig
from dllama_tpu.quants import blocks
from dllama_tpu.runtime.generate import Engine
from dllama_tpu.runtime.sampler import SamplerConfig


def tiny_cfg():
    return ModelConfig(
        arch="llama", dim=128, hidden_dim=256, n_layers=2, n_heads=4, n_kv_heads=4,
        vocab_size=128, seq_len=64, head_size=32, kv_dim=128, dtype="float32",
    )


@pytest.mark.parametrize("kind", ["q40", "q80"])
def test_quantized_forward_close_to_dense(kind):
    cfg = tiny_cfg()
    params = llama.random_params(cfg, seed=0)
    qparams = llama.quantize_params(params, kind)
    rope = llama.rope_tables(cfg)
    tokens = jnp.asarray([1, 5, 9], jnp.int32)

    dense_logits, _ = llama.forward(cfg, params, rope, tokens, llama.init_cache(cfg), 0)
    # reference for error: dense forward with *dequantized* weights — isolates
    # kernel error from quantization error
    deq = {
        "embedding": params["embedding"],
        "rms_final": params["rms_final"],
        "wcls": _deq(qparams["wcls"]),
        "layers": {
            k: (_deq(v) if k in llama.QUANTIZABLE else v)
            for k, v in qparams["layers"].items()
        },
    }
    deq_logits, _ = llama.forward(cfg, deq, rope, tokens, llama.init_cache(cfg), 0)
    q_logits, _ = llama.forward(cfg, qparams, rope, tokens, llama.init_cache(cfg), 0)

    # kernel vs dequantized-dense: only bf16 tile rounding apart
    np.testing.assert_allclose(
        np.asarray(q_logits), np.asarray(deq_logits), rtol=0.05, atol=0.02
    )
    # quantization itself stays sane vs the full-precision model
    corr = np.corrcoef(
        np.asarray(q_logits).reshape(-1), np.asarray(dense_logits).reshape(-1)
    )[0, 1]
    assert corr > 0.95, corr  # 4-bit error on random (outlier-free) weights


def _deq(qt):
    from dllama_tpu.ops import qmatmul

    return jnp.asarray(qmatmul.dequantize(qt), jnp.float32)



def test_engine_decodes_with_quantized_params():
    cfg = tiny_cfg()
    params = llama.quantize_params(llama.random_params(cfg, seed=1), "q40")
    eng = Engine(cfg, params, SamplerConfig(temperature=0.0, seed=7))
    toks = [t for t, _ in eng.generate([1, 2, 3], steps=5)]
    assert len(toks) == 5
    assert all(0 <= t < cfg.vocab_size for t in toks)
    # fused loop agrees with the step-by-step loop at temperature 0
    eng2 = Engine(cfg, params, SamplerConfig(temperature=0.0, seed=7))
    fused, _, _ = eng2.generate_fused([1, 2, 3], steps=5)
    assert fused == toks


def moe_cfg(arch="mixtral"):
    return ModelConfig(
        arch=arch, dim=128, hidden_dim=256, n_layers=2, n_heads=4, n_kv_heads=4,
        vocab_size=128, seq_len=64, head_size=32, kv_dim=128, n_experts=4,
        n_active_experts=2, rope_style="half", dtype="float32",
    )


@pytest.mark.parametrize("arch", ["mixtral", "grok1"])
def test_quantized_moe_forward_close_to_dense(arch):
    """Quantized expert stacks (per-expert fused kernels) vs the dense einsum
    path on the same dequantized weights — the MoE analogue of the dense
    check above (reference: Q40 experts at
    `/root/reference/src/transformer.cpp:479-487`)."""
    cfg = moe_cfg(arch)
    params = llama.random_params(cfg, seed=3)
    qparams = llama.quantize_params(params, "q40")
    rope = llama.rope_tables(cfg)
    tokens = jnp.asarray([1, 5, 9], jnp.int32)

    deq = {
        "embedding": params["embedding"],
        "rms_final": params["rms_final"],
        "wcls": _deq(qparams["wcls"]),
        "layers": {
            k: (_deq(v) if k in llama.QUANTIZABLE + llama.MOE_QUANTIZABLE else v)
            for k, v in qparams["layers"].items()
        },
    }
    deq_logits, _ = llama.forward(cfg, deq, rope, tokens, llama.init_cache(cfg), 0)
    q_logits, _ = llama.forward(cfg, qparams, rope, tokens, llama.init_cache(cfg), 0)
    np.testing.assert_allclose(
        np.asarray(q_logits), np.asarray(deq_logits), rtol=0.05, atol=0.05
    )


def test_engine_decodes_quantized_moe():
    cfg = moe_cfg()
    params = llama.quantize_params(llama.random_params(cfg, seed=4), "q40")
    eng = Engine(cfg, params, SamplerConfig(temperature=0.0, seed=7))
    toks = [t for t, _ in eng.generate([1, 2, 3], steps=4)]
    assert len(toks) == 4 and all(0 <= t < cfg.vocab_size for t in toks)


@pytest.mark.parametrize("arch", ["mixtral", "grok1"])
def test_moe_decode_selected_matches_dense_combine(arch):
    """T==1 quantized MoE runs only the top-k selected experts
    (moe._moe_decode_selected, index-steered kernels); a T==2 batch with the
    same row duplicated takes the all-experts dense-combine path through the
    SAME kernels. Row 0 must agree — the combine weights are zero off the
    top-k, so the selected path drops only exact-zero terms."""
    from dllama_tpu.models import moe

    cfg = moe_cfg(arch)
    qlayers = llama.quantize_params(llama.random_params(cfg, seed=5), "q40")["layers"]
    lp = {
        k: (v if hasattr(v, "kind") else v[0]) for k, v in qlayers.items()
    }  # the layer-0 view the scalar-prefetch scan builds
    xb = jnp.asarray(np.random.default_rng(6).standard_normal((1, cfg.dim)),
                     jnp.float32)

    sel = moe.moe_ffn(cfg, lp, xb, layer=jnp.int32(0))          # selected path
    both = moe.moe_ffn(cfg, lp, jnp.concatenate([xb, xb]), layer=jnp.int32(0))
    np.testing.assert_allclose(
        np.asarray(sel[0]), np.asarray(both[0]), rtol=2e-4, atol=2e-4
    )


def test_moe_mixed_dense_quant_experts_under_layer_scan():
    """A quant MoE checkpoint can have SOME expert stacks fall back to dense
    (hidden_dim % 64 != 0 path) while others quantize. Under the layer scan
    the dense stack arrives layer-indexed and the quant stacks layer-stacked;
    each must be handled per-leaf (regression: a global quant gate fed the
    4D [L, E, ...] quant stack into the per-layer expert scan)."""
    cfg = moe_cfg()
    qparams = llama.quantize_params(llama.random_params(cfg, seed=8), "q40")
    mixed = dict(qparams)
    mixed["layers"] = dict(qparams["layers"])
    mixed["layers"]["moe_down"] = _deq(qparams["layers"]["moe_down"])  # dense
    rope = llama.rope_tables(cfg)

    for tokens in (jnp.asarray([3], jnp.int32), jnp.asarray([3, 4, 5], jnp.int32)):
        mixed_logits, _ = llama.forward(
            cfg, mixed, rope, tokens, llama.init_cache(cfg), 0)
        q_logits, _ = llama.forward(
            cfg, qparams, rope, tokens, llama.init_cache(cfg), 0)
        np.testing.assert_allclose(
            np.asarray(mixed_logits), np.asarray(q_logits), rtol=0.05, atol=0.05
        )


def test_quant_reader_loads_moe(tmp_path):
    """quant_params_from_reader on a Q40 Mixtral file: expert stacks arrive as
    per-expert QuantTensors whose dequantized bits equal the file's."""
    from dllama_tpu.formats.spec import ArchType
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.ops import qmatmul

    cfg = moe_cfg()
    spec = ModelSpec(
        arch=ArchType.MIXTRAL, dim=cfg.dim, hidden_dim=cfg.hidden_dim,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads,
        vocab_size=cfg.vocab_size, seq_len=cfg.seq_len,
        n_experts=cfg.n_experts, n_active_experts=cfg.n_active_experts,
        weights_float_type=blocks.Q40,
    )
    rng = np.random.default_rng(5)
    path = str(tmp_path / "tiny_moe_q40.m")
    write_model(
        path, spec,
        {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(np.float32)
         for e in tensor_plan(spec)},
    )
    with WeightFileReader(path) as reader:
        qp = llama.quant_params_from_reader(reader, cfg, "q40", fuse=False)
        up_file = reader.read_tensor("layers.0.experts.1.up", np.float32).T
    up = qp["layers"]["moe_up"]
    from dllama_tpu.ops.qmatmul import QuantTensor

    assert isinstance(up, QuantTensor) and up.w.shape[:2] == (cfg.n_layers, cfg.n_experts)
    got = qmatmul.dequantize(jax.tree.map(lambda x: x[0, 1], up))
    np.testing.assert_array_equal(got, up_file)

    # and the engine decodes with it
    eng = Engine(cfg, qp, SamplerConfig(temperature=0.0))
    toks, _, _ = eng.generate_fused([1, 2], steps=3)
    assert len(toks) == 3


def test_quant_reader_lossless_repack(tmp_path):
    """Writing a Q40 file then loading via quant_params_from_reader must give
    exactly the file's dequantized values (no second quantization)."""
    cfg = tiny_cfg()
    from dllama_tpu.formats.spec import ArchType

    spec = ModelSpec(
        arch=ArchType.LLAMA, dim=cfg.dim, hidden_dim=cfg.hidden_dim,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads,
        vocab_size=cfg.vocab_size, seq_len=cfg.seq_len,
        weights_float_type=blocks.Q40,
    )
    params = llama.random_params(cfg, seed=2)
    path = str(tmp_path / "tiny_q40.m")
    with ModelWriter(path, spec) as w:
        for e in w.plan:
            name = e.name
            if name == "token_embedding":
                w.write_next(name, params["embedding"])
            elif name == "rms_final":
                w.write_next(name, params["rms_final"])
            elif name == "wcls":
                w.write_next(name, np.asarray(params["wcls"]).T)
            else:
                layer = int(name.split(".")[1])
                field = name.split(".")[2]
                t = np.asarray(params["layers"][field][layer])
                w.write_next(name, t.T if t.ndim == 2 else t)

    with WeightFileReader(path) as reader:
        qp = llama.quant_params_from_reader(reader, cfg, "q40", fuse=False)
        # dequantized kernel weights == file's decoded tensors, bit for bit
        w1_file = reader.read_tensor("layers.0.w1", np.float32).T  # [in, out]
    from dllama_tpu.ops import qmatmul

    w1_kernel = qmatmul.dequantize(_layer0(qp["layers"]["w1"]))
    np.testing.assert_array_equal(w1_kernel, w1_file)


def _layer0(qt):
    import jax

    return jax.tree.map(lambda x: x[0], qt)


@pytest.mark.parametrize("kind", ["q40", "q80"])
def test_fused_qkv_ffn_matches_unfused(kind):
    """fuse_qkv_ffn (wq|wk|wv -> wqkv, w1|w3 -> w13) must be numerically
    identical: the concat moves whole output columns with their scales."""
    cfg = tiny_cfg()
    qparams = llama.quantize_params(llama.random_params(cfg, seed=6), kind)
    fused = llama.fuse_qkv_ffn(qparams)
    assert "wqkv" in fused["layers"] and "wq" not in fused["layers"]
    assert "w13" in fused["layers"] and "w1" not in fused["layers"]

    rope = llama.rope_tables(cfg)
    tokens = jnp.asarray([1, 5, 9], jnp.int32)
    a, _ = llama.forward(cfg, qparams, rope, tokens, llama.init_cache(cfg), 0)
    b, _ = llama.forward(cfg, fused, rope, tokens, llama.init_cache(cfg), 0)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_fused_moe_upgate_matches_unfused():
    cfg = moe_cfg()
    qparams = llama.quantize_params(llama.random_params(cfg, seed=7), "q40")
    fused = llama.fuse_qkv_ffn(qparams)
    assert "moe_upgate" in fused["layers"] and "moe_up" not in fused["layers"]
    rope = llama.rope_tables(cfg)
    tokens = jnp.asarray([2, 4], jnp.int32)
    a, _ = llama.forward(cfg, qparams, rope, tokens, llama.init_cache(cfg), 0)
    b, _ = llama.forward(cfg, fused, rope, tokens, llama.init_cache(cfg), 0)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_engine_autofuses_quant_params_single_device():
    cfg = tiny_cfg()
    qparams = llama.quantize_params(llama.random_params(cfg, seed=8), "q40")
    eng = Engine(cfg, qparams, SamplerConfig(temperature=0.0))
    assert "wqkv" in eng.params["layers"]
    toks, _, _ = eng.generate_fused([1, 2, 3], steps=5)

    # independent unfused baseline: greedy-decode by hand through
    # llama.forward on the ORIGINAL (unfused) params
    rope = llama.rope_tables(cfg)
    cache = llama.init_cache(cfg)
    prms = jax.tree.map(jnp.asarray, qparams)
    logits, cache = llama.forward(cfg, prms, rope, jnp.asarray([1, 2, 3], jnp.int32), cache, 0)
    want = []
    tok = int(np.argmax(np.asarray(logits[-1])))
    pos = 3
    for _ in range(5):
        want.append(tok)
        logits, cache = llama.forward(
            cfg, prms, rope, jnp.asarray([tok], jnp.int32), cache, jnp.int32(pos)
        )
        tok = int(np.argmax(np.asarray(logits[0])))
        pos += 1
    assert toks == want
