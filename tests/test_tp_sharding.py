"""Sharding-invariance tests on the 8-virtual-device CPU mesh: running the
same model over tp in {1,2,4,8} must reproduce the unsharded result — the
TPU analogue of the reference's slicing-invariance test
(`/root/reference/src/transformer-test.cpp:6-84`), extended to the full
forward pass and the decode engine (the reference has no automated
multi-node test at all, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models import llama
from dllama_tpu.parallel.mesh import TP, make_mesh, tp_mesh
from dllama_tpu.parallel.sharding import check_tp_compatible, param_specs, shard_params
from dllama_tpu.runtime.generate import Engine
from dllama_tpu.runtime.sampler import SamplerConfig

from tests.test_llama_forward import tiny_cfg


def big_enough_cfg():
    # n_kv_heads=8 so tp=8 divides it
    return tiny_cfg(n_heads=8, n_kv_heads=8, dim=128, kv_dim=128, head_size=16, vocab_size=128)


@pytest.mark.parametrize("n_tp", [2, 4, 8])
def test_forward_invariant_under_tp(n_tp):
    cfg = big_enough_cfg()
    params = llama.random_params(cfg, seed=13)
    rope = llama.rope_tables(cfg)
    tokens = jnp.asarray([3, 77, 12, 5], jnp.int32)

    base, _ = llama.forward(
        cfg, jax.tree.map(jnp.asarray, params), rope, tokens, llama.init_cache(cfg), 0
    )

    mesh = tp_mesh(n_tp)
    sharded = shard_params(params, mesh, cfg)
    with mesh:
        got, _ = llama.forward(cfg, sharded, rope, tokens, llama.init_cache(cfg), 0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("n_tp", [2, 8])
def test_generation_invariant_under_tp(n_tp):
    cfg = big_enough_cfg()
    params = llama.random_params(cfg, seed=21)
    base = Engine(cfg, params, SamplerConfig(temperature=0.0))
    want = [t for t, _ in base.generate([1, 9, 4], steps=6)]

    eng = Engine(cfg, params, SamplerConfig(temperature=0.0), mesh=tp_mesh(n_tp))
    got = [t for t, _ in eng.generate([1, 9, 4], steps=6)]
    assert got == want


def test_tp_constraint_enforced():
    cfg = big_enough_cfg()  # 8 kv heads
    with pytest.raises(ValueError, match="nSlices<=nKvHeads|n_kv_heads"):
        check_tp_compatible(cfg, 3)
    cfg2 = tiny_cfg()  # 2 kv heads
    with pytest.raises(ValueError):
        shard_params(llama.random_params(cfg2, seed=0), tp_mesh(4), cfg2)


def test_param_specs_cover_params():
    cfg = big_enough_cfg()
    params = llama.random_params(cfg, seed=0)
    specs = param_specs(cfg, 8)
    # identical tree structure: every param leaf has a spec
    jax.tree.map(lambda a, s: None, params, specs)


def test_sharded_placement_row_and_col():
    """wq shards its out axis, wo its in axis — the reference's Row/Col split."""
    cfg = big_enough_cfg()
    mesh = tp_mesh(4)
    sharded = shard_params(llama.random_params(cfg, seed=0), mesh, cfg)
    wq_shard = sharded["layers"]["wq"].sharding.spec
    wo_shard = sharded["layers"]["wo"].sharding.spec
    assert wq_shard == (None, None, TP)
    assert wo_shard == (None, TP, None)
    # local shard sizes: wq [L, dim, dim/4], wo [L, dim/4, dim]
    shard_shapes = {s.data.shape for s in sharded["layers"]["wq"].addressable_shards}
    assert shard_shapes == {(cfg.n_layers, cfg.dim, cfg.dim // 4)}


def test_make_mesh_axes():
    mesh = make_mesh({"dp": 2, "tp": 4})
    assert mesh.shape == {"dp": 2, "tp": 4}
    with pytest.raises(ValueError):
        make_mesh({"dp": 16, "tp": 4})


def test_sharded_decode_step_emits_collectives():
    """Guard against the closure-capture trap: params passed to jit as
    constants get replicated and the 'tensor-parallel' program compiles with
    zero collectives. The real TP program must contain all-reduces."""
    cfg = big_enough_cfg()
    eng = Engine(cfg, llama.random_params(cfg, seed=0), SamplerConfig(temperature=0.0),
                 mesh=tp_mesh(8))
    cache = eng.new_cache()
    lowered = eng._decode_step.func.lower(
        eng.params, eng.rope, cache, jnp.asarray(5, jnp.int32), jnp.int32(0),
        jax.random.PRNGKey(0), jnp.float32(0.0), jnp.float32(0.9),
        jnp.zeros((), jnp.bool_))
    hlo = lowered.compile().as_text()
    assert hlo.count("all-reduce") > 0
    # and the weights really live sharded: 1/8th per device
    shapes = {s.data.shape for s in eng.params["layers"]["wq"].addressable_shards}
    assert shapes == {(cfg.n_layers, cfg.dim, cfg.dim // 8)}


def test_streaming_sharded_load_matches_full_load(tmp_path):
    """sharded_params_from_reader (per-tensor streaming onto the mesh) must
    produce the exact pytree of shard_params(params_from_reader(...))."""
    import numpy as np

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.formats.weights import WeightFileReader
    from dllama_tpu.models import llama
    from dllama_tpu.models.config import ModelConfig
    from dllama_tpu.parallel.mesh import tp_mesh
    from dllama_tpu.parallel.sharding import shard_params, sharded_params_from_reader
    from dllama_tpu.quants import blocks

    spec = ModelSpec(
        arch=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2, n_heads=4,
        n_kv_heads=4, vocab_size=96, seq_len=32, weights_float_type=blocks.F32,
    )
    rng = np.random.default_rng(8)
    path = str(tmp_path / "m.m")
    write_model(path, spec, {
        e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(np.float32)
        for e in tensor_plan(spec)
    })

    mesh = tp_mesh(4)
    with WeightFileReader(path) as r:
        cfg = ModelConfig.from_spec(r.spec, dtype="float32")
        streamed = sharded_params_from_reader(r, cfg, mesh)
    with WeightFileReader(path) as r:
        full = shard_params(llama.params_from_reader(r, cfg), mesh, cfg)

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        streamed, full,
    )
    # and the shardings themselves agree
    jax.tree.map(lambda a, b: (a.sharding == b.sharding) or (_ for _ in ()).throw(
        AssertionError((a.sharding, b.sharding))), streamed, full)


def test_dense_tp_wire_estimate_matches_compiled_hlo_structure():
    """The dense-pjit S/R estimate assumes XLA lowers each layer to 2
    dim-payload all-reduces (attention out + FFN out). Audit the COMPILED
    HLO: the layer scan's while-body must contain exactly that collective
    pair and nothing weight-scale-sized beyond it — if XLA's lowering ever
    changes shape, this fails and the estimate (marked '~' in the CLI)
    must be rederived."""
    import re

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dllama_tpu.models import llama
    from dllama_tpu.models.config import ModelConfig
    from dllama_tpu.parallel.mesh import tp_mesh
    from dllama_tpu.runtime.generate import Engine
    from dllama_tpu.runtime.sampler import SamplerConfig

    cfg = ModelConfig(
        arch="llama", dim=256, hidden_dim=512, n_layers=2, n_heads=8,
        n_kv_heads=8, vocab_size=384, seq_len=64, head_size=32, kv_dim=256,
        dtype="float32",
    )
    eng = Engine(cfg, llama.random_params(cfg, seed=0, dtype=np.float32),
                 SamplerConfig(temperature=0.0), mesh=tp_mesh(8))
    assert not eng.wire_stats_exact  # dense path: estimate, marked '~'
    cache = eng.new_cache()
    txt = eng._decode_step.func.lower(
        eng.params, eng.rope, cache, jnp.asarray(3, jnp.int32), jnp.int32(0),
        jax.random.PRNGKey(0), jnp.float32(0.0), jnp.float32(0.9),
        jnp.zeros((), jnp.bool_),
    ).compile().as_text()

    ops = re.findall(
        r"=\s+\w+\[([^\]]*)\][^\n]*?\b(all-reduce|all-gather|reduce-scatter)\(",
        txt,
    )

    def numel(dims: str) -> int:
        ns = [int(d) for d in dims.split(",") if d.strip().isdigit()]
        return int(np.prod(ns)) if ns else 1

    # activation-scale collectives (>= dim elements); sampling/top-p emits
    # only small or scalar ones
    big = [(dims, op) for dims, op in ops if numel(dims) >= cfg.dim]
    dim_reduces = [x for x in big if x[1] == "all-reduce"
                   and numel(x[0]) == cfg.dim]
    # the scan body appears ONCE in the HLO and executes n_layers times:
    # exactly the 2-per-layer pair the analytic estimate prices
    assert len(dim_reduces) == 2, big
    # nothing bigger than dim moves per layer (a hidden-sized collective
    # would mean the estimate undercounts ~2x)
    leftovers = [x for x in big if x not in dim_reduces
                 and numel(x[0]) > cfg.dim]
    # the final logits all-gather (vocab-sized) is the one allowed big op
    assert all(numel(d) <= cfg.vocab_size for d, _ in leftovers), leftovers
