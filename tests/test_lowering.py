"""Static TPU tiling verifier (ops.lowering) — the CPU gate for Mosaic.

The round-2 bench (BENCH_r02) was the only run to reach a real TPU backend,
and it failed inside our own kernel: the q40 scale-plane BlockSpec produced
a (4, 1024) block against the (172, 4096) array — the last two block dims
must each be divisible by the (8, 128) min tile or equal to the array dim.
These tests prove, without a TPU, that every pallas_call in the inventory
satisfies that contract for every real model shape, and that the verifier
still *recognizes* the historical failure when fed the legacy plan.
"""

import jax.numpy as jnp
import pytest

from dllama_tpu.ops import lowering, qmatmul
from dllama_tpu.ops.lowering import MODEL_DIMS, SWEEP_T, TilingError


# ---------------------------------------------------------------------------
# The pinned BENCH_r02 regression case
# ---------------------------------------------------------------------------

def test_pinned_bench_r02_shape_passes_for_every_kernel():
    """Llama-2-7B q40 down-projection (K=11008, O=4096) — the exact shape
    whose scale plane was (172, 4096) on hardware — must pass the verifier
    through the PACKED path (K_MULTIPLE padding) for every kernel variant."""
    for L in (None, 32):
        for fused in (False, True):
            plans = lowering.check("q40", dict(
                T=1, K=11008, O=4096, L=L, nosub=True, fused_norm=fused))
            assert plans, "check returned no plans"
            for p in plans:
                assert not p.violations()


def test_pinned_bench_r02_legacy_plan_is_flagged():
    """Feeding the UNpadded K (k_padded=11008, the pre-K_MULTIPLE packing)
    must reproduce the historical violation signature: bk=256 gives a
    (4, 1024) scale block against the (172, 4096) plane."""
    with pytest.raises(TilingError) as ei:
        lowering.check("q40", dict(T=1, K=11008, O=4096, k_padded=11008))
    msg = str(ei.value)
    assert "(4, 1024)" in msg and "(172, 4096)" in msg, msg


def test_verifier_catches_raw_sublane_violation():
    """Direct OperandPlan check: a 4-row f32 block in an 8-sublane world."""
    op = lowering.OperandPlan("s", (172, 4096), (4, 1024), "float32")
    v = op.violations()
    assert len(v) == 1 and "sublane" in v[0]


def test_verifier_dtype_aware_sublane():
    """Sublane minimum widens with narrower dtypes: 8 rows is fine for f32,
    a violation for bf16 (16) and int8 (32) unless equal to the dim."""
    assert not lowering.OperandPlan("x", (64, 256), (8, 128), "float32").violations()
    assert lowering.OperandPlan("x", (64, 256), (8, 128), "bfloat16").violations()
    assert lowering.OperandPlan("x", (64, 256), (16, 128), "bfloat16").violations() == []
    assert lowering.OperandPlan("x", (64, 256), (16, 128), "int8").violations()
    # equal-to-dim escape: whole-array blocks lower at any size
    assert not lowering.OperandPlan("x", (4, 100), (4, 100), "int8").violations()


def test_verifier_checks_lane_dim():
    op = lowering.OperandPlan("x", (64, 384), (8, 192), "float32")
    v = op.violations()
    assert len(v) == 1 and "lane" in v[0]


# ---------------------------------------------------------------------------
# The full CPU sweep: 7B/8B/MoE x q40/q80 x T in {1,8,64} (+ flash, + rope)
# ---------------------------------------------------------------------------

def test_full_sweep_zero_violations():
    report = lowering.sweep()
    bad = {case: [v for p in plans for v in p["violations"]]
           for case, plans in report.items()
           if any(p["violations"] for p in plans)}
    assert not bad, bad
    # the matrix really covers what it claims
    assert len(report) > 400
    for name, *_ in MODEL_DIMS:
        for kind in ("q40", "q80"):
            for T in SWEEP_T:
                assert f"{name}/{kind}/down/T{T}" in report
    assert "llama2_7b/flash/T1/float8_e4m3fn" in report
    assert "llama2_7b/rope_cache/B8/T9/float8_e4m3fn" in report


@pytest.mark.parametrize("kind", ["q40", "q80"])
@pytest.mark.parametrize("T", SWEEP_T)
def test_plan_matches_real_tile_plan(kind, T):
    """The verifier must derive blocks from the SAME tile_plan the launchers
    call — if the planner and the plan drift, the gate is meaningless."""
    K, O = 4096, 11008
    kp = qmatmul._pad_up(K, qmatmul.K_MULTIPLE[kind])
    bk, bo = qmatmul.tile_plan(kind, kp, O)
    (plan,) = lowering.lowering_plan(kind, dict(T=T, K=K, O=O, nosub=False))
    note = plan.note
    assert f"bk={bk}" in note and f"bo={bo}" in note
    x = plan.operands[0]
    assert x.block[-1] == (bk // 2 if kind == "q40" else bk)


def test_flash_plans_cover_f8_cache():
    """The standing "hardware-validate f8" item, lowerability half: the f8
    cache dtype must pass the verifier at every swept flash shape (1-byte
    itemsize -> 32-sublane minimum, satisfied by whole-dim cache blocks and
    the BLOCK_S=256 VMEM scratch)."""
    for T in (1, 8):
        plans = lowering.check("flash_decode", dict(
            T=T, L=32, S=4096, n_heads=32, n_kv_heads=8, head_size=128,
            cache_dtype="float8_e4m3fn"))
        names = {o.name for p in plans for o in p.operands}
        assert "k_buf[scratch]" in names


def test_rope_cache_plans_all_wrappers():
    """Solo (B=1), batched (T=1) and verify (B x T) wrappers all plan
    clean, for every cache dtype the caches support."""
    for dt in ("bfloat16", "float32", "float8_e4m3fn"):
        for B, T, name in ((1, 4, "rope_cache_update"),
                           (8, 1, "rope_cache_update_batched"),
                           (8, 9, "rope_cache_update_verify")):
            (plan,) = lowering.check("rope_cache", dict(
                T=T, B=B, L=32, S=2048, n_kv_heads=8, head_size=128,
                cache_dtype=dt, batched=B > 1))
            assert plan.kernel == name
            assert plan.grid == (B,)


def test_main_json_report(capsys):
    """The CI artifact: --json emits a machine-readable report with case
    count and violation count."""
    import json

    rc = lowering.main(["--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["n_violations"] == 0
    assert report["n_cases"] == len(report["cases"]) > 400


def test_tile_cell_cap_respected_across_sweep():
    """No planned compute block may exceed the VMEM cell cap the tile
    planner enforces — guards against a future tile_plan edit raising
    blocks past what fits."""
    for kind in ("q40", "q80"):
        for _, dim, hidden, *_ in MODEL_DIMS:
            for K, O in ((dim, hidden), (hidden, dim)):
                kp = qmatmul._pad_up(K, qmatmul.K_MULTIPLE[kind])
                bk, bo = qmatmul.tile_plan(kind, kp, O)
                assert bk * bo <= qmatmul._TILE_CELL_CAP


def test_unknown_kind_raises():
    with pytest.raises(ValueError):
        lowering.lowering_plan("conv2d", dict(K=1, O=1))
