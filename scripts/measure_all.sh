#!/bin/bash
# One-shot measurement battery for a live TPU. Run from the repo root the
# moment the axon tunnel is up; every result lands in results/ with a
# timestamp so a flaky tunnel mid-run loses nothing already captured.
#
#   bash scripts/measure_all.sh [results_dir]
#
# Order is deliberate: the headline benches first (worth the most if the
# tunnel dies again), then kernel experiments, then the slower e2e drives.
set -u
OUT=${1:-results}
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%S)
log() { echo "== $* ($(date -u +%H:%M:%S))" | tee -a "$OUT/measure_$STAMP.log"; }
run() { # run <name> <cmd...>: capture stdout+stderr, never abort the battery
  local name=$1; shift
  log "$name: $*"
  # Hard per-command timeout: a wedged axon tunnel blocks forever otherwise.
  # GNU timeout (non-foreground) runs the command in its own process group
  # and signals the whole group, so grandchildren (native_e2e spawns make +
  # dllama-native) die too and can't keep the single-session tunnel starved.
  local T=${CMD_TIMEOUT:-1500}
  timeout -k 30 "$T" "$@" >"$OUT/${name}_$STAMP.out" 2>&1
  local rc=$?
  { [ $rc -eq 124 ] || [ $rc -eq 137 ]; } && log "$name TIMED OUT after ${T}s (rc=$rc)"
  log "$name rc=$rc"
  tail -3 "$OUT/${name}_$STAMP.out" | tee -a "$OUT/measure_$STAMP.log"
}

# 0. tunnel sanity + a guaranteed green number: TinyLlama shape is the
#    cheapest end-to-end decode (r02's only green driver number); if the
#    tunnel dies mid-battery, this one already banked a measurement
CMD_TIMEOUT=900 run bench_tiny env BENCH_MODEL=tiny BENCH_DEADLINE_S=840 python bench.py
# 1. headline: Llama-2-7B q40 single-chip (the vs_baseline metric)
run bench_7b python bench.py
# 2. the north-star model shape
run bench_8b env BENCH_MODEL=llama3 python bench.py
# 3. batched-decode throughput headline (8 sequences per weight stream)
run bench_7b_batch8 env BENCH_BATCH=8 python bench.py
# 4. f8 KV cache variant
run bench_7b_f8 env BENCH_CACHE=f8 python bench.py
# 4b. Mixtral-shape MoE: the selected-experts q40 decode path
run bench_moe env BENCH_MODEL=moe python bench.py
# 5. q40 kernel variant shootout (pick the winner for ops/qmatmul.py)
run qkernel python scripts/qkernel_experiments.py all
# 6. decode ablation (where the remaining ms go)
run ablate python scripts/ablate_decode.py
# 7. kernel microbench reference points
run kernel_bench python scripts/kernel_bench.py
# 8. native runtime end to end (exports, builds, drives dllama-native)
run native_e2e python scripts/native_e2e.py /tmp/dllama_native_e2e_$STAMP

log "battery done — results in $OUT/"
