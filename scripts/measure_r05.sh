#!/bin/bash
# Round-5 measurement battery — VERDICT r04's "only non-negotiable" is a
# driver-green perf record, so the order is: headline decode first (nosub
# default end-to-end), then the flash/f8 long-context matrix (flash now
# composes with f8 caches AND dense engines), the ablation that localizes
# the ~4 ms non-kernel overhead, the kernel shootout incl. the new
# E/F/G variants (int8-MXU, 2048-lane O tiles, bf16 correction planes),
# prefill, MoE/Grok shapes, and the two e2e proofs (native, train->serve).
#
#   bash scripts/measure_r05.sh [results_dir]
#
# Probe-and-wait before every stage (the single-session relay wedges after
# a client dies); TUNNEL_DEAD short-circuits once a wait exhausts.
set -u
OUT=${1:-results}
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%S)
log() { echo "== $* ($(date -u +%H:%M:%S))" | tee -a "$OUT/measure_$STAMP.log"; }

probe_tunnel() {
  timeout -k 10 150 python -c '
import time, jax, jax.numpy as jnp
t0 = time.time()
jax.block_until_ready(jnp.ones((256, 256), jnp.bfloat16) @ jnp.ones((256, 256), jnp.bfloat16))
print(f"TUNNEL_OK {time.time()-t0:.1f}s")' 2>&1 | grep -q TUNNEL_OK
}
TUNNEL_DEAD=0
wait_tunnel() {
  local i
  [ "$TUNNEL_DEAD" = 1 ] && return 1
  for i in $(seq 1 8); do
    probe_tunnel && return 0
    log "tunnel not answering (probe $i/8), waiting"
    [ "$i" -lt 8 ] && sleep 240
  done
  TUNNEL_DEAD=1
  return 1
}

run() {
  local name=$1; shift
  if ! wait_tunnel; then
    log "$name SKIPPED: tunnel never answered"
    return
  fi
  log "$name: $*"
  local T=${CMD_TIMEOUT:-1500}
  timeout -k 30 "$T" "$@" >"$OUT/${name}_$STAMP.out" 2>&1
  local rc=$?
  { [ $rc -eq 124 ] || [ $rc -eq 137 ]; } && log "$name TIMED OUT after ${T}s (rc=$rc)"
  log "$name rc=$rc"
  tail -3 "$OUT/${name}_$STAMP.out" | tee -a "$OUT/measure_$STAMP.log"
}

# ---- headline: the driver's own metric, nosub default -------------------
CMD_TIMEOUT=900 run bench_7b env BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_8b env BENCH_MODEL=llama3 BENCH_DEADLINE_S=840 python bench.py
# ---- flash/f8 long-context matrix (seq 4096 is where they earn keep) ----
CMD_TIMEOUT=900 run bench_7b_seq4k env BENCH_SEQ=4096 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_seq4k_flash env BENCH_SEQ=4096 DLLAMA_FLASH_DECODE=1 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_seq4k_f8 env BENCH_SEQ=4096 BENCH_CACHE=f8 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_seq4k_f8_flash env BENCH_SEQ=4096 BENCH_CACHE=f8 DLLAMA_FLASH_DECODE=1 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_flash env DLLAMA_FLASH_DECODE=1 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_seq2k_flash env BENCH_SEQ=2048 DLLAMA_FLASH_DECODE=1 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_seq2k env BENCH_SEQ=2048 BENCH_DEADLINE_S=840 python bench.py
# ---- where the non-kernel ms go (VERDICT next #2) -----------------------
run ablate_r05 python scripts/ablate_decode.py
# ---- kernel shootout incl. the new E/F/G variants (next #6) -------------
run qkernel_r05 python scripts/qkernel_experiments.py all
run kernel_bench_r05 python scripts/kernel_bench.py
# ---- prefill + batch throughput ----------------------------------------
CMD_TIMEOUT=900 run bench_7b_prefill env BENCH_PREFILL=448 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_batch8 env BENCH_BATCH=8 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_batch8_seq1k_flash env BENCH_BATCH=8 BENCH_SEQ=1024 DLLAMA_FLASH_DECODE=1 BENCH_DEADLINE_S=840 python bench.py
# ---- speculative decoding (solo + batched-verify composition) -----------
CMD_TIMEOUT=900 run bench_7b_spec8 env BENCH_SPEC=8 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_spec8_batch4 env BENCH_SPEC=8 BENCH_BATCH=4 BENCH_DEADLINE_S=840 python bench.py
# ---- other model shapes -------------------------------------------------
CMD_TIMEOUT=900 run bench_tiny env BENCH_MODEL=tiny BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_moe env BENCH_MODEL=moe BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_grok env BENCH_MODEL=grok BENCH_DEADLINE_S=840 python bench.py
# ---- the two e2e proofs (VERDICT next #4/#5) ----------------------------
# each phase is its own process so the single-session relay is never held
# by a parent while its child waits for a session (the r04 rc=124 lesson)
run native_e2e_r05 python scripts/native_e2e.py /tmp/dllama_native_e2e_$STAMP
run train_e2e_r05 python scripts/train_tiny_e2e.py results/train_tiny_e2e_r05 --no-cli
run train_e2e_cli_r05 python scripts/train_tiny_e2e.py results/train_tiny_e2e_r05 --cli-only

log "r05 battery done — results in $OUT/"
