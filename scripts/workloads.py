"""Deterministic workload generator for the serving stack.

Every scenario is a pure function ``(seed, knobs) -> [Req|Conv]`` built
on a private ``random.Random(seed)``: the same seed always produces the
same prompts, the same arrival offsets, the same class mix — so a
regression hunt can replay the exact traffic that tripped a gate.
Scenarios model the traffic the fleet actually has to survive:

    bursty        interactive bursts arriving while long batch-class
                  jobs saturate the batch lane (the preemption mix)
    longctx       prompts sized near the context window
    multiturn     conversations whose turns share a growing prefix
                  (radix-cache reuse traffic)
    disconnects   abusive clients that drop the socket mid-SSE
    killburst     a pure interactive burst sized for the replica-SIGKILL
                  drill (the kill itself is orchestrated by the caller —
                  this module only speaks HTTP)

The runner fires each request at its deterministic offset, measures
TTFT (request start -> first content delta) and TPOT, and returns one
result dict per request. ``BENCH_WORKLOADS`` (bench.py) wires these
into a gated battery; standalone use replays a scenario against any
running replica or router:

    JAX_PLATFORMS=cpu python scripts/workloads.py --port 9990 \
        --scenario bursty --seed 0 --out report.json

Stdlib only — importable from CPU smoke jobs without touching jax.
"""

from __future__ import annotations

import argparse
import http.client
import json
import random
import sys
import threading
import time

#: fixed lexicon the prompt builder draws from — tokenizes to plain
#: bytes under the synthetic test vocabs, so prompt length in tokens
#: tracks prompt length in characters
WORDS = ("alpha", "bravo", "cedar", "delta", "ember", "fjord", "gamma",
         "haze", "iris", "jolt", "karst", "lumen", "mesa", "noble",
         "onyx", "pylon", "quartz", "ridge", "sable", "tundra", "umber",
         "vertex", "willow", "xenon", "yonder", "zephyr")


class Req:
    """One scheduled request: fire at ``at_s`` after the run starts."""

    __slots__ = ("at_s", "name", "slo_class", "messages", "max_tokens",
                 "stream", "disconnect")

    def __init__(self, at_s, name, slo_class, messages, max_tokens,
                 stream=True, disconnect=False):
        self.at_s = at_s
        self.name = name
        self.slo_class = slo_class
        self.messages = messages
        self.max_tokens = max_tokens
        self.stream = stream
        self.disconnect = disconnect


class Conv:
    """A multi-turn conversation: turns run sequentially, each carrying
    the full transcript so far (the prefix-reuse traffic shape)."""

    __slots__ = ("at_s", "name", "slo_class", "user_turns", "max_tokens")

    def __init__(self, at_s, name, slo_class, user_turns, max_tokens):
        self.at_s = at_s
        self.name = name
        self.slo_class = slo_class
        self.user_turns = user_turns
        self.max_tokens = max_tokens


def _sentence(rng: random.Random, words: int) -> str:
    return " ".join(rng.choice(WORDS) for _ in range(words))


# ---- scenario generators (pure: seed -> schedule) ---------------------

def bursty_mix(seed=0, bursts=3, burst_size=4, gap_s=2.0, batch_jobs=2,
               batch_tokens=320, interactive_tokens=16):
    """Long batch-class jobs admitted first, then interactive bursts
    landing on top — the mix the preemption gate is specified against."""
    rng = random.Random(seed)
    reqs = []
    for j in range(batch_jobs):
        reqs.append(Req(
            0.0, f"batch-{j}", "batch",
            [{"role": "user",
              "content": f"[job {j}] {_sentence(rng, 8)}"}],
            batch_tokens))
    for b in range(bursts):
        base = 0.5 + b * gap_s
        for i in range(burst_size):
            reqs.append(Req(
                base + rng.uniform(0.0, 0.25), f"int-{b}-{i}",
                "interactive",
                [{"role": "user",
                  "content": f"[{b}/{i}] {_sentence(rng, 5)}"}],
                interactive_tokens))
    return reqs


def long_context(seed=0, n=3, target_chars=300, max_tokens=24,
                 slo_class="interactive"):
    """Prompts sized near the window: ``target_chars`` of lexicon text
    (roughly that many tokens under the byte-level test vocabs)."""
    rng = random.Random(seed)
    reqs = []
    for i in range(n):
        parts = []
        while sum(len(p) + 1 for p in parts) < target_chars:
            parts.append(_sentence(rng, 6) + ".")
        reqs.append(Req(
            i * 0.4, f"longctx-{i}", slo_class,
            [{"role": "user", "content": " ".join(parts)}], max_tokens))
    return reqs


def multi_turn(seed=0, conversations=2, turns=3, max_tokens=16,
               slo_class="interactive"):
    rng = random.Random(seed)
    convs = []
    for c in range(conversations):
        opener = _sentence(rng, 6)
        users = [f"[conv {c}] {opener}"] + [
            f"then {_sentence(rng, 4)}" for _ in range(turns - 1)]
        convs.append(Conv(c * 0.3, f"conv-{c}", slo_class, users,
                          max_tokens))
    return convs


def abusive_disconnects(seed=0, n=3, max_tokens=64):
    """Streams whose client vanishes right after the first content
    delta — the server must reap the row, not leak it."""
    rng = random.Random(seed)
    return [Req(i * 0.3, f"abuser-{i}", "interactive",
                [{"role": "user",
                  "content": f"[drop {i}] {_sentence(rng, 5)}"}],
                max_tokens, disconnect=True)
            for i in range(n)]


def kill_burst(seed=0, n=6, max_tokens=48):
    """A pure interactive streamed burst for the SIGKILL drill: every
    request must survive the caller killing a replica mid-burst."""
    rng = random.Random(seed)
    return [Req(0.15 * i, f"kill-{i}", "interactive",
                [{"role": "user",
                  "content": f"[kill {i}] {_sentence(rng, 5)}"}],
                max_tokens)
            for i in range(n)]


def diurnal(seed=0, cycles=2, bursts_per_cycle=3, burst_size=4,
            burst_gap_s=1.5, idle_s=10.0, max_tokens=24):
    """Bursty-diurnal replay for the elastic-fleet gate: each cycle is a
    busy window of interactive bursts followed by a long idle trough —
    the shape where a static fleet pays for capacity the trough never
    uses, and an elastic one must grow into the burst and shed back down
    without a single client-visible error. The same prompt repeats within
    a cycle on purpose: it becomes the router's hot prefix, the material
    a scale-up pre-warms into the joining replica."""
    rng = random.Random(seed)
    reqs, t, k = [], 0.5, 0
    for c in range(cycles):
        refrain = _sentence(rng, 5)
        for b in range(bursts_per_cycle):
            for i in range(burst_size):
                reqs.append(Req(
                    t + 0.05 * i, f"diurnal-{c}-{k}", "interactive",
                    [{"role": "user", "content": f"[cycle {c}] {refrain}"}],
                    max_tokens))
                k += 1
            t += burst_gap_s
        t += idle_s
    return reqs


SCENARIOS = {
    "bursty": bursty_mix,
    "longctx": long_context,
    "multiturn": multi_turn,
    "disconnects": abusive_disconnects,
    "killburst": kill_burst,
    "diurnal": diurnal,
}


# ---- the runner -------------------------------------------------------

def sse_parts(data: bytes):
    """-> (content_text, n_deltas, saw_done, error-or-None)."""
    text, n, done, err = [], 0, False, None
    for ev in data.split(b"\n\n"):
        for line in ev.split(b"\n"):
            if not line.startswith(b"data: "):
                continue
            payload = line[6:]
            if payload == b"[DONE]":
                done = True
                continue
            try:
                obj = json.loads(payload)
            except ValueError:
                continue
            if "error" in obj:
                err = obj["error"].get("message")
            for ch in obj.get("choices", []):
                piece = (ch.get("delta") or {}).get("content")
                if piece:
                    text.append(piece)
                    n += 1
    return "".join(text), n, done, err


def do_request(host: str, port: int, rq: Req, timeout: float = 300.0,
               headers: dict = None) -> dict:
    """Fire one request NOW; returns the measured result record. A
    ``disconnect`` request closes the socket right after its first
    content delta (``disconnected: True``) — by design a torn stream,
    not an error."""
    body = {"model": "workloads", "messages": rq.messages,
            "max_tokens": rq.max_tokens, "temperature": 0.0,
            "stream": rq.stream}
    hdrs = {"Content-Type": "application/json",
            "X-Dllama-Class": rq.slo_class}
    if headers:
        hdrs.update(headers)
    out = {"name": rq.name, "slo_class": rq.slo_class, "status": None,
           "ttft_ms": None, "total_ms": None, "tpot_ms": None,
           "text": "", "done": False, "error": None,
           "disconnected": False}
    t0 = time.perf_counter()
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/chat/completions",
                     json.dumps(body).encode(), headers=hdrs)
        resp = conn.getresponse()
        out["status"] = resp.status
        if resp.status != 200:
            raw = resp.read()
            try:
                out["error"] = json.loads(raw)["error"]["message"]
            except (ValueError, KeyError, TypeError):
                out["error"] = raw[:200].decode("utf-8", "replace")
            out["retry_after"] = resp.getheader("Retry-After")
            return out
        if not rq.stream:
            raw = resp.read()
            out["total_ms"] = out["ttft_ms"] = \
                (time.perf_counter() - t0) * 1000.0
            try:
                obj = json.loads(raw)
                out["text"] = obj["choices"][0]["message"]["content"]
                out["done"] = True
            except (ValueError, KeyError, IndexError, TypeError) as e:
                out["error"] = f"malformed body: {e}"
            return out
        buf = b""
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            buf += chunk
            if out["ttft_ms"] is None and b'"content"' in buf:
                out["ttft_ms"] = (time.perf_counter() - t0) * 1000.0
                if rq.disconnect:
                    out["disconnected"] = True
                    return out  # finally: the socket dies mid-stream
            if buf.endswith(b"data: [DONE]\n\n"):
                break
        out["total_ms"] = (time.perf_counter() - t0) * 1000.0
        text, n, done, err = sse_parts(buf)
        out["text"], out["done"], out["error"] = text, done, err
        if not done and err is None:
            out["error"] = "stream ended without [DONE]"
        if (out["ttft_ms"] is not None and n > 1
                and out["total_ms"] is not None):
            out["tpot_ms"] = (out["total_ms"] - out["ttft_ms"]) / (n - 1)
        return out
    except OSError as e:
        out["error"] = f"transport: {e}"
        return out
    finally:
        conn.close()


def run_conversation(host: str, port: int, conv: Conv,
                     timeout: float = 300.0) -> list:
    """Sequential turns, each carrying the transcript so far. Stops at
    the first failed turn."""
    msgs, results = [], []
    for t, user in enumerate(conv.user_turns):
        msgs.append({"role": "user", "content": user})
        r = do_request(host, port,
                       Req(0.0, f"{conv.name}-t{t}", conv.slo_class,
                           list(msgs), conv.max_tokens), timeout)
        results.append(r)
        if r["status"] != 200 or r["error"]:
            break
        msgs.append({"role": "assistant", "content": r["text"]})
    return results


def run_schedule(host: str, port: int, schedule: list, actions=(),
                 timeout: float = 300.0) -> list:
    """Replay a scenario: every Req fires at ``start + at_s`` on its own
    thread; a Conv occupies one thread for its sequential turns.
    ``actions`` is ``[(at_s, callable)]`` for out-of-band chaos (e.g.
    the bench's replica SIGKILL). Returns one result per Req plus one
    per conversation TURN, in schedule order."""
    start = time.perf_counter() + 0.05
    slots = [None] * len(schedule)

    def fire(i, item):
        delay = start + item.at_s - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        if isinstance(item, Conv):
            slots[i] = run_conversation(host, port, item, timeout)
        else:
            slots[i] = [do_request(host, port, item, timeout)]

    threads = [threading.Thread(target=fire, args=(i, item), daemon=True)
               for i, item in enumerate(schedule)]
    for at_s, fn in actions:
        threads.append(threading.Thread(
            target=lambda at_s=at_s, fn=fn: (
                time.sleep(max(0.0, start + at_s - time.perf_counter())),
                fn()),
            daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [r for slot in slots if slot for r in slot]


def pct(values, q: float):
    """Nearest-rank percentile; None for an empty sample."""
    if not values:
        return None
    xs = sorted(values)
    return xs[min(len(xs) - 1, max(0, int(round(q / 100.0 * len(xs))) - 1))]


def summarize(results: list) -> dict:
    """Per-class rollup: counts, error list, TTFT p50/p95/p99, TPOT p50.
    Deliberate disconnects are counted, never errors."""
    by = {}
    for r in results:
        c = by.setdefault(r["slo_class"], {
            "n": 0, "ok": 0, "disconnected": 0, "errors": [],
            "_ttft": [], "_tpot": []})
        c["n"] += 1
        if r["disconnected"]:
            c["disconnected"] += 1
        elif r["status"] == 200 and not r["error"]:
            c["ok"] += 1
        else:
            c["errors"].append(
                f"{r['name']}: {r['status']} {r['error']!r}")
        if r["ttft_ms"] is not None:
            c["_ttft"].append(r["ttft_ms"])
        if r["tpot_ms"] is not None:
            c["_tpot"].append(r["tpot_ms"])
    out = {}
    for cls, c in by.items():
        out[cls] = {
            "n": c["n"], "ok": c["ok"],
            "disconnected": c["disconnected"], "errors": c["errors"],
            "ttft_p50_ms": pct(c["_ttft"], 50),
            "ttft_p95_ms": pct(c["_ttft"], 95),
            "ttft_p99_ms": pct(c["_ttft"], 99),
            "tpot_p50_ms": pct(c["_tpot"], 50),
        }
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--scenario", choices=sorted(SCENARIOS) + ["all"],
                    default="bursty")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="write the full per-request results JSON here")
    args = ap.parse_args()
    names = sorted(SCENARIOS) if args.scenario == "all" \
        else [args.scenario]
    report, bad = {}, False
    for name in names:
        schedule = SCENARIOS[name](seed=args.seed)
        t0 = time.perf_counter()
        results = run_schedule(args.host, args.port, schedule)
        summ = summarize(results)
        report[name] = {"wall_s": round(time.perf_counter() - t0, 2),
                        "summary": summ, "results": results}
        for cls, c in summ.items():
            if c["errors"]:
                bad = True
        print(f"[{name}] " + json.dumps(summ, sort_keys=True))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
