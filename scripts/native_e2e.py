"""End-to-end test of the native PJRT runtime on the real TPU.

Run OUTSIDE pytest's CPU-forced env (fresh process, default backend):

    python scripts/native_e2e.py /tmp/native_export

Exports a tiny random Llama + a synthetic vocab with the current backend's
PJRT plugin options in the manifest, builds native/, then runs
``dllama-native generate`` against the plugin and checks it emits tokens.
Exits 0 on success.

Session discipline (the r04 battery's rc=124 lesson): the axon relay serves
ONE session. The export phase runs in a SUBPROCESS that exits (releasing
the session) before ``dllama-native`` creates its own client — the
coordinating parent never touches the backend (importing jax is safe: the
sitecustomize's register() sets the plugin env vars without claiming a
session; claiming happens at PJRT_Client_Create).
"""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def export_phase(out_dir: str) -> int:
    """Touches the backend: export model + tokenizer, then EXIT."""
    import jax.numpy as jnp

    from dllama_tpu import export_native
    from dllama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer
    from dllama_tpu.models import llama
    from dllama_tpu.models.config import ModelConfig

    cfg = ModelConfig(
        arch="llama", dim=128, hidden_dim=256, n_layers=2, n_heads=4,
        n_kv_heads=4, vocab_size=259, seq_len=64, head_size=32, kv_dim=128,
        dtype="bfloat16",
    )
    params = llama.device_random_params(cfg, seed=0)
    export_native.export_model(
        cfg, params, out_dir, cache_dtype=jnp.bfloat16, model_name="tiny-e2e"
    )

    # byte-level vocab: 3 specials + 256 byte tokens = 259 == cfg.vocab_size
    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [f"<0x{b:02X}>".encode() for b in range(256)]
    tok = TokenizerData(vocab=vocab, scores=[0.0] * len(vocab), bos_id=1, eos_id=2)
    write_tokenizer(os.path.join(out_dir, "tokenizer.t"), tok)
    print("export phase done")
    return 0


def main() -> int:
    args = [a for a in sys.argv[1:] if a != "--export-only"]
    out_dir = args[0] if args else "/tmp/dllama_native_e2e"
    if "--export-only" in sys.argv:
        return export_phase(out_dir)

    # phase 1 in a subprocess: its clean exit releases the relay session
    # before the native binary asks for one
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), out_dir, "--export-only"],
        timeout=900, cwd=REPO,
    )
    if proc.returncode != 0:
        print("❌ export phase failed")
        return 1
    time.sleep(5)  # give the single-session relay a beat to recycle

    native = os.path.join(REPO, "native")
    subprocess.run(["make", "-j4"], cwd=native, check=True)
    proc = subprocess.run(
        [
            os.path.join(native, "build", "dllama-native"), "generate",
            "--export-dir", out_dir,
            # long enough that the bucketed prefill path engages (44 byte
            # tokens land in ONE prefill dispatch instead of 43 steps)
            "--prompt", "the quick brown fox jumps over the lazy dog",
            "--steps", "8",
            "--temperature", "0",
        ],
        capture_output=True,
        timeout=600,
    )
    stdout = proc.stdout.decode("utf-8", errors="replace")
    sys.stderr.write(proc.stderr.decode("utf-8", errors="replace"))
    sys.stdout.write(stdout)
    if proc.returncode != 0:
        print("❌ dllama-native failed")
        return 1
    if "Generated tokens" not in stdout:
        print("❌ no generation stats in output")
        return 1
    stderr = proc.stderr.decode("utf-8", errors="replace")
    # "📄 prompt: N tokens in D dispatches" MUST be present and show batching
    # (this run's 44-token prompt fits one 64-token prefill dispatch); a
    # missing line means the prefill path silently stopped engaging
    import re

    mt = re.search(r"prompt: (\d+) tokens in (\d+) dispatches", stderr)
    if not mt:
        print("❌ no prompt-dispatch stats line in stderr")
        return 1
    if int(mt.group(2)) >= int(mt.group(1)) - 1:
        print("❌ prefill did not batch the prompt "
              f"({mt.group(1)} tokens, {mt.group(2)} dispatches)")
        return 1
    print("✅ native e2e OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
