"""Decode-latency ablation on the real TPU — finds where the ms/token go.

Times each variant as ONE fused scanned program (per-dispatch tunnel latency
is ~3.5 ms on this box, so isolated kernel timings are meaningless). Variants:

  full        the production fused decode step (fused wqkv/w13 kernels)
  unfused     same but per-matrix kernels (pre-fusion layout)
  matmuls     per-layer quant matmuls only (no attention/norms/sampling)
  no_wcls     full minus the final vocab projection
  bf16        dense bf16 weights (the non-quant baseline)

Usage: python scripts/ablate_decode.py [tiny|7b] [steps]
"""

import sys
import time

import jax

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from _platform import apply_platform_override  # noqa: E402

apply_platform_override(jax)
import jax.numpy as jnp

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(
    __import__("os").path.abspath(__file__))))

from bench import LLAMA2_7B, TINYLLAMA_1_1B  # noqa: E402
from dllama_tpu.models import llama  # noqa: E402
from dllama_tpu.models.config import ModelConfig  # noqa: E402
from dllama_tpu.ops.qmatmul import QuantTensor, matmul_any  # noqa: E402
from dllama_tpu.runtime.generate import Engine  # noqa: E402
from dllama_tpu.runtime.sampler import SamplerConfig  # noqa: E402


def timed(label, fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def engine_variant(cfg, params, steps, fuse_quant=True):
    eng = Engine(cfg, params, SamplerConfig(temperature=0.0),
                 cache_dtype=jnp.bfloat16, fuse_quant=fuse_quant)
    eng.generate_fused([1], steps=steps)  # compile
    t0 = time.perf_counter()
    eng.generate_fused([1], steps=steps)
    return (time.perf_counter() - t0) * 1000.0 / steps


def matmuls_only(cfg, params, steps):
    """Scan of per-layer quant matmuls with data dependency, no attention."""

    # layers MUST be a traced argument, not a closure capture: jit bakes
    # captured arrays in as constants, and shipping a 7B model's 3.5 GB of
    # quant planes as compile-time literals wedges the tunnel for minutes
    # (observed: the r04 battery ablate timing out at 1500 s right here)
    @jax.jit
    def run(x, layers):
        def step(x, _):
            def layer(x, lp):
                names = [n for n in ("wqkv", "wq", "wk", "wv") if n in lp]
                acc = 0.0
                for n in names:
                    acc = acc + matmul_any(x, lp[n])[:, : cfg.dim].sum()
                o = matmul_any(x, lp["wo"])
                h13 = lp.get("w13")
                if h13 is not None:
                    h = matmul_any(x, h13)
                    half = h.shape[-1] // 2
                    h = h[:, :half] + h[:, half:]
                else:
                    h = matmul_any(x, lp["w1"]) + matmul_any(x, lp["w3"])
                d = matmul_any(h, lp["w2"])
                return x + (o + d) * 0.0 + acc * 0.0, None

            x, _ = jax.lax.scan(layer, x, layers)
            return x, x[0, 0]

        x, ys = jax.lax.scan(step, x, None, length=steps)
        return ys.sum()

    x = jnp.ones((1, cfg.dim), jnp.bfloat16)
    dt = timed("matmuls", run, x, params["layers"])
    return dt * 1000.0 / steps


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "7b"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    cfg = ModelConfig(**(LLAMA2_7B if which == "7b" else TINYLLAMA_1_1B))
    print(f"backend={jax.default_backend()} model={which} steps={steps}")

    qp = llama.device_random_quant_params(cfg, kind="q40", seed=0)
    jax.block_until_ready(qp)

    fused = llama.fuse_qkv_ffn(qp)
    print(f"full (fused):   {engine_variant(cfg, dict(fused), steps):8.3f} ms/token")
    print(f"matmuls only:   {matmuls_only(cfg, fused, steps):8.3f} ms/token (fused)")
    print(f"full (unfused): {engine_variant(cfg, qp, steps, fuse_quant=False):8.3f} ms/token")
    print(f"matmuls only:   {matmuls_only(cfg, qp, steps):8.3f} ms/token (unfused)")

    # no-wcls: replace the classifier with a tiny dense matrix
    import dataclasses

    nw = dict(fused)
    nw["wcls"] = jnp.zeros((cfg.dim, 128), jnp.bfloat16)
    cfg_small_vocab = dataclasses.replace(cfg, vocab_size=128)
    print(f"tiny wcls:      {engine_variant(cfg_small_vocab, nw, steps):8.3f} ms/token")

    del qp, fused, nw
    jax.clear_caches()
    bp = llama.device_random_params(cfg, seed=0)
    print(f"bf16 dense:     {engine_variant(cfg, bp, steps):8.3f} ms/token")


if __name__ == "__main__":
    main()
