"""Collate a measurement battery's banked records into one markdown table.

Scans ``results/*_<stamp>.out`` files for the single-line JSON records the
bench emits (and the shootout/ablation's plain-text lines), newest stamp per
stage name, and prints a markdown summary ready to paste into RESULTS_r05.md.

Usage: python scripts/collect_results.py [results_dir]
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys


def latest_per_stage(results_dir: str) -> dict:
    """{stage: path} for the newest timestamped .out of each stage."""
    stages: dict = {}
    for path in sorted(glob.glob(os.path.join(results_dir, "*_*.out"))):
        base = os.path.basename(path)
        m = re.match(r"(.+)_(\d{8}T\d{6})\.out$", base)
        if not m:
            continue
        name, stamp = m.groups()
        if name not in stages or stamp > stages[name][0]:
            stages[name] = (stamp, path)
    return {k: v[1] for k, v in stages.items()}


def last_json(path: str):
    rec = None
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if line.startswith("{") and line.endswith("}"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    pass
    return rec


def main() -> int:
    results_dir = sys.argv[1] if len(sys.argv) > 1 else "results"
    stages = latest_per_stage(results_dir)
    if not stages:
        print(f"no staged .out records in {results_dir}/")
        return 1

    bench_rows = []
    other = []
    for name in sorted(stages):
        path = stages[name]
        rec = last_json(path)
        if rec and "metric" in rec:
            err = rec.get("error")
            bench_rows.append(
                (name, rec.get("metric"), rec.get("value"),
                 rec.get("weights"), rec.get("vs_baseline"),
                 f" ERROR: {err}" if err else ""))
        else:
            # shootout/ablation/e2e stages: surface their last few lines
            with open(path, errors="replace") as f:
                tail = [ln.rstrip() for ln in f.readlines() if ln.strip()][-6:]
            other.append((name, tail))

    if bench_rows:
        dash = lambda v: "—" if v is None else v  # noqa: E731
        print("| stage | metric | ms/token | weights | vs baseline | note |")
        print("|---|---|---|---|---|---|")
        for name, metric, value, weights, vs, err in bench_rows:
            print(f"| {name} | {metric} | {dash(value)} | {dash(weights)} |"
                  f" {dash(vs)} | {err.strip() or '—'} |")
        print()
    for name, tail in other:
        print(f"### {name}")
        for ln in tail:
            print(f"    {ln}")
        print()
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:  # | head etc. closing stdout is not an error
        raise SystemExit(0)
