"""CI disaggregation drill: migration must be exact, and a killed
transfer must be invisible to the client.

Topology: one dedicated-prefill and one dedicated-decode ``cli serve``
subprocess (tiny synthetic weights, CPU) behind an IN-PROCESS router —
the drill holds the replica Popen handles, which is what makes the
SIGKILL leg deterministic. The prefill replica boots with a
``kv_export:slow`` fault armed AFTER its first export, so the second
migration has a wide-open transfer window to die in.

Three legs, all must hold:

1. **Exactness** — a chat request through the router migrates
   (prefill -> KV page stream -> decode) and its answer, buffered AND
   streamed, is byte-equal to the same request served end-to-end by one
   replica directly. The router's ``outcome="ok"`` migration counter,
   both replicas' export/import counters, and the federated
   ``dllama_kv_transfer_*`` families (one HELP/TYPE each, replica
   labels) must all show it.
2. **SIGKILL mid-transfer** — the prefill replica is killed while its
   (slowed) export is in flight. The client must still get HTTP 200
   with the exact same answer: the router degrades to a full re-prefill
   on the surviving decode replica, counted as a fallback outcome —
   zero client-visible errors across the whole drill.
3. **Liveness after loss** — the fleet keeps serving normal traffic
   with the prefill replica gone (the migration path simply closes).

Artifacts written to --out-dir (uploaded by CI):
    verdict.json                 per-leg verdict + counter evidence
    router_metrics.txt           the in-process router's exposition
    metrics_fleet.txt            the federated /metrics/fleet body
    replica-prefill.log / replica-decode.log

Usage:  JAX_PLATFORMS=cpu python scripts/disagg_drill.py
            [--out-dir disagg-drill]
Exit 0 only if every leg holds.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def request(port, method, path, body=None, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    ctype = resp.getheader("Content-Type") or ""
    conn.close()
    return resp.status, ctype, data


def chat(**kw):
    body = {"model": "m", "max_tokens": 16, "temperature": 0.0,
            "messages": [{"role": "user", "content": "hi hi migrate me"}]}
    body.update(kw)
    return body


def sse_text(data: bytes) -> str:
    out = []
    for line in data.decode("utf-8", "replace").splitlines():
        if line.startswith("data: ") and line != "data: [DONE]":
            delta = json.loads(line[6:])["choices"][0].get("delta") or {}
            out.append(delta.get("content", ""))
    return "".join(out)


def counter_values(text: str, family: str) -> dict:
    """{label_block: value} for one family in a Prometheus exposition."""
    out = {}
    for line in text.splitlines():
        if not line.startswith(family):
            continue
        sample, _, value = line.rpartition(" ")
        try:
            out[sample[len(family):]] = float(value)
        except ValueError:
            pass
    return out


def wait_ready(port: int, proc, deadline_s: float = 300.0) -> None:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica :{port} exited {proc.returncode} before ready")
        try:
            status, _, _ = request(port, "GET", "/ready", timeout=2)
            if status == 200:
                return
        except OSError:
            pass  # not listening yet
        time.sleep(0.5)
    raise RuntimeError(f"replica :{port} never became ready")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-dir", default="disagg-drill")
    args = ap.parse_args()
    out = os.path.abspath(args.out_dir)
    os.makedirs(out, exist_ok=True)

    import numpy as np

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.tokenizer_file import (TokenizerData,
                                                   write_tokenizer)
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.quants import blocks
    from dllama_tpu.serving import router as router_mod

    art = os.path.join(out, "artifacts")
    os.makedirs(art, exist_ok=True)
    model, tokp = os.path.join(art, "m.m"), os.path.join(art, "t.t")
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=300, seq_len=96,
                     weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    write_model(model, spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(
                    np.float32) for e in tensor_plan(spec)})
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
             + [b"hi"] * 41)
    write_tokenizer(tokp, TokenizerData(
        vocab=vocab, scores=[0.0] * 300, bos_id=1, eos_id=2))

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU children must not register
    #   the axon TPU plugin (single-session tunnel blocks a 2nd registrant)
    env.pop("DLLAMA_FAULTS", None)

    def spawn(role: str, port: int, extra_env: dict = None):
        log = open(os.path.join(out, f"replica-{role}.log"), "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dllama_tpu.cli", "serve",
             "--model", model, "--tokenizer", tokp,
             "--host", "127.0.0.1", "--port", str(port),
             "--role", role, "--kv-pages", "16",
             "--batch-window", "5", "--batch-max", "2", "--tp", "1"],
            env=dict(env, **(extra_env or {})), cwd=REPO,
            stdout=log, stderr=subprocess.STDOUT, start_new_session=True)
        log.close()
        return proc

    p_port, d_port = free_port(), free_port()
    # leg 1 performs two exports (buffered + SSE); the THIRD stalls 8s so
    # leg 2's SIGKILL lands squarely inside an in-flight transfer, not in
    # a lucky gap between requests
    p_proc = spawn("prefill", p_port,
                   {"DLLAMA_FAULTS": "kv_export:slow:delay_ms=8000,after=2"})
    d_proc = spawn("decode", d_port)

    failures = []
    evidence: dict = {}
    state = None
    rsrv = None
    try:
        wait_ready(p_port, p_proc)
        wait_ready(d_port, d_proc)
        print(f"replicas up: prefill :{p_port}  decode :{d_port}")

        state = router_mod.RouterState(
            [router_mod.Replica("127.0.0.1", p_port),
             router_mod.Replica("127.0.0.1", d_port)],
            probe_interval_s=0.3)
        state.probe_once()
        if not state.disagg_ready():
            raise RuntimeError(
                "router does not see a prefill+decode fleet: "
                + json.dumps([r.snapshot() for r in state.replicas]))
        state.start_probes()
        rsrv = router_mod.create_router_server(state, host="127.0.0.1",
                                               port=0)
        r_port = rsrv.server_address[1]
        threading.Thread(target=rsrv.serve_forever, daemon=True).start()
        print(f"router up: :{r_port} (kv wire {state.kv_wire})")

        def migrations() -> dict:
            snap = state.metrics.snapshot().get(
                "dllama_kv_transfer_migrations_total", {})
            return {v["labels"]["outcome"]: v["value"]
                    for v in snap.get("values", [])}

        # -- leg 1: exactness -----------------------------------------
        # reference: the decode replica serving the SAME request alone
        status, _, data = request(d_port, "POST", "/v1/chat/completions",
                                  chat())
        if status != 200:
            raise RuntimeError(f"solo reference returned {status}")
        solo = json.loads(data)["choices"][0]["message"]["content"]

        status, _, data = request(r_port, "POST", "/v1/chat/completions",
                                  chat())
        if status != 200:
            failures.append(f"migrated request returned {status}")
        else:
            got = json.loads(data)["choices"][0]["message"]["content"]
            if got != solo:
                failures.append(
                    f"migrated answer diverged: {got!r} != solo {solo!r}")

        status, ctype, data = request(r_port, "POST", "/v1/chat/completions",
                                      chat(stream=True))
        if status != 200 or "text/event-stream" not in ctype:
            failures.append(
                f"migrated SSE request returned {status} ({ctype})")
        elif sse_text(data) != solo:
            failures.append(
                f"migrated SSE answer diverged: {sse_text(data)!r}")

        evidence["migrations_after_leg1"] = migrations()
        if migrations().get("ok", 0) < 2:
            failures.append(
                f"expected >=2 ok migrations, got {migrations()}")

        # counters on both sides of the wire, and their federated view
        _, _, p_metrics = request(p_port, "GET", "/metrics", timeout=30)
        _, _, d_metrics = request(d_port, "GET", "/metrics", timeout=30)
        exports = counter_values(p_metrics.decode(),
                                 "dllama_kv_transfer_exports_total")
        imports = counter_values(d_metrics.decode(),
                                 "dllama_kv_transfer_imports_total")
        evidence["prefill_exports"] = exports
        evidence["decode_imports"] = imports
        if exports.get('{outcome="ok"}', 0) < 2:
            failures.append(f"prefill replica exports: {exports}")
        if imports.get('{outcome="ok"}', 0) < 2:
            failures.append(f"decode replica imports: {imports}")
        _, _, fed = request(r_port, "GET", "/metrics/fleet", timeout=30)
        fed = fed.decode()
        with open(os.path.join(out, "metrics_fleet.txt"), "w") as f:
            f.write(fed)
        for fam in ("dllama_kv_transfer_exports_total",
                    "dllama_kv_transfer_bytes_total"):
            if fed.count(f"# HELP {fam}") != 1:
                failures.append(f"/metrics/fleet HELP for {fam} not deduped")
            if f'{fam}{{replica="127.0.0.1:' not in fed:
                failures.append(f"/metrics/fleet lacks labeled {fam}")
        print(f"leg 1 done: migrations {migrations()}")

        # -- leg 2: SIGKILL the prefill replica mid-transfer ----------
        def kill_prefill():
            time.sleep(1.5)  # inside the 8s slowed export, after admit
            os.kill(p_proc.pid, signal.SIGKILL)
            print("SIGKILLed the prefill replica mid-export")

        killer = threading.Thread(target=kill_prefill, daemon=True)
        killer.start()
        t0 = time.monotonic()
        status, _, data = request(r_port, "POST", "/v1/chat/completions",
                                  chat())
        killer.join()
        evidence["leg2_latency_s"] = round(time.monotonic() - t0, 2)
        if status != 200:
            failures.append(
                f"request during prefill death returned {status} "
                f"(must degrade, never error)")
        else:
            got = json.loads(data)["choices"][0]["message"]["content"]
            if got != solo:
                failures.append(
                    f"fallback answer diverged: {got!r} != solo {solo!r}")
        mig = migrations()
        evidence["migrations_after_leg2"] = mig
        if not (mig.get("prefill_fallback") or mig.get("no_prefill")):
            failures.append(
                f"no fallback outcome counted after the kill: {mig}")

        # -- leg 3: the fleet keeps serving without its prefill half --
        for i in range(2):
            status, _, data = request(r_port, "POST", "/v1/chat/completions",
                                      chat())
            if status != 200:
                failures.append(f"post-kill request #{i} returned {status}")
            elif json.loads(data)["choices"][0]["message"]["content"] != solo:
                failures.append(f"post-kill answer #{i} diverged")
        print(f"legs 2+3 done: migrations {mig}")

        with open(os.path.join(out, "router_metrics.txt"), "w") as f:
            f.write(state.metrics.render())
    except Exception as e:
        failures.append(f"drill aborted: {e!r}")
    finally:
        if state is not None:
            state.stop_probes()
        if rsrv is not None:
            rsrv.shutdown()
        for proc in (p_proc, d_proc):
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()

    verdict = {"ok": not failures, "failures": failures,
               "evidence": evidence}
    with open(os.path.join(out, "verdict.json"), "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        return 1
    print("disaggregation drill: exact migration + invisible transfer "
          "death + post-loss liveness all verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
