"""Shared backend-forcing escape hatch for the measurement scripts.

The container's sitecustomize pins JAX at the axon TPU tunnel; with the
tunnel down, the FIRST backend touch hangs forever. ``DLLAMA_PLATFORM=cpu``
forces the platform via jax.config (the env var alone is too late — the
sitecustomize already imported jax), mirroring bench.py and the CLI.

Usage, immediately after ``import jax`` and before any backend use::

    from _platform import apply_platform_override
    apply_platform_override(jax)
"""

import os


def apply_platform_override(jax_module) -> None:
    forced = os.environ.get("DLLAMA_PLATFORM")
    if forced:
        jax_module.config.update("jax_platforms", forced)
