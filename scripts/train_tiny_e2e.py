"""Train → convert → serve, end to end, with zero network access.

The reference's purpose is serving *published* Q40 checkpoints
(`/root/reference/download-model.py:5-26`). This environment has no egress,
so this script produces the closest verifiable equivalent: it TRAINS a tiny
byte-level Llama on an embedded corpus with the framework's own training
step, writes the weights through the real `.m` writer as Q40 (the same
format + quantizer published checkpoints use), writes a real `.t` byte
tokenizer, then drives `dllama_tpu.cli generate` on the files as a
subprocess — proving the whole publish-side and serve-side pipeline:

    make_train_step → ModelWriter(q40) → WeightFileReader →
    quant_params_from_reader → Engine decode → sane text out.

"Sane text" is checkable because the model memorizes the corpus: greedy
decoding from a corpus prefix must reproduce the corpus continuation
(the same determinism check as the reference's `examples/macbeth.sh`).

Usage:  python scripts/train_tiny_e2e.py [outdir] [--steps N] [--no-cli]
Writes  outdir/tiny.m, outdir/tiny.t, outdir/e2e_result.json
Exit 0 only if the generated continuation matches the corpus.

Session discipline on the TPU (the r04 battery's rc=124 lesson): the axon
relay serves ONE session, so a parent that holds it starves its own CLI
child forever. Run the two halves as separate processes there:

    python scripts/train_tiny_e2e.py outdir --no-cli     # train + in-process
    python scripts/train_tiny_e2e.py outdir --cli-only   # CLI drive, fresh

``--cli-only`` never touches the backend in the parent — the CLI subprocess
gets the whole session. (Off-TPU the combined run stays fine: the child is
forced onto CPU.)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The corpus the model memorizes: the same Macbeth soliloquy the reference's
# determinism example feeds (`/root/reference/examples/macbeth.sh` uses it as
# a long prompt; here it is the training set). Public-domain Shakespeare.
CORPUS = (
    "Tomorrow, and tomorrow, and tomorrow, creeps in this petty pace "
    "from day to day, to the last syllable of recorded time; and all our "
    "yesterdays have lighted fools the way to dusty death. Out, out, brief "
    "candle! Life's but a walking shadow, a poor player that struts and "
    "frets his hour upon the stage, and then is heard no more. It is a tale "
    "told by an idiot, full of sound and fury, signifying nothing. "
)


def build_byte_tokenizer(path: str):
    """A real `.t` file with byte-fallback-only vocab: 3 specials + 256 byte
    tokens. Encoding any text works via the tokenizer's byte fallback; no
    merges needed for a memorization demo."""
    from dllama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer

    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
    tok = TokenizerData(vocab=vocab, scores=[0.0] * len(vocab), bos_id=1, eos_id=2)
    write_tokenizer(path, tok)
    return tok


#: prompt/expected split shared by the in-process and CLI gates (tokens of
#: one full-corpus encoding; byte vocab maps token n to CORPUS[n-1])
N_PROMPT, N_STEPS = 100, 85


def drive_cli(outdir: str, child_on_cpu: bool) -> bool:
    """THE CLI-drive block, shared by the combined off-TPU flow and the
    --cli-only phase so the command, tolerance, and verdict can't drift.
    ``child_on_cpu``: scrub the relay env vars and force the child onto CPU
    (off-TPU runs; without it a dead tunnel hangs the child)."""
    m_path = os.path.join(outdir, "tiny.m")
    t_path = os.path.join(outdir, "tiny.t")
    prompt = CORPUS[:N_PROMPT - 1]
    expected = CORPUS[N_PROMPT - 1:N_PROMPT - 1 + N_STEPS]
    env = dict(os.environ, PYTHONPATH=REPO)
    if child_on_cpu:
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.cli", "generate",
         "--model", m_path, "--tokenizer", t_path,
         "--prompt", prompt, "--steps", str(N_STEPS),
         "--temperature", "0"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=1200)
    # same 95% tolerance as the in-process gate: require the expected
    # prefix, not the whole continuation verbatim
    cli_ok = (proc.returncode == 0
              and expected[:int(0.95 * len(expected))] in proc.stdout)
    print(f"CLI generate: rc={proc.returncode} match={cli_ok}")
    if not cli_ok:
        print(proc.stdout[-1500:])
        print(proc.stderr[-1500:])
    return cli_ok


def cli_phase(outdir: str) -> int:
    """--cli-only: drive the CLI on an existing outdir — backend untouched
    in this process (see module docstring), so the child gets the whole
    relay session on TPU. Merges its verdict into e2e_result.json. The
    child goes to CPU when the operator forced this process off the TPU
    (decided from env alone — touching the backend to ask would claim the
    very session the child needs)."""
    m_path = os.path.join(outdir, "tiny.m")
    t_path = os.path.join(outdir, "tiny.t")
    res_path = os.path.join(outdir, "e2e_result.json")
    if not (os.path.exists(m_path) and os.path.exists(t_path)):
        print(f"--cli-only but {m_path} / {t_path} missing "
              "(run the training phase first)")
        return 2
    child_on_cpu = (os.environ.get("DLLAMA_PLATFORM") == "cpu"
                    or os.environ.get("JAX_PLATFORMS") == "cpu"
                    or not os.environ.get("PALLAS_AXON_POOL_IPS"))
    cli_ok = drive_cli(outdir, child_on_cpu)
    result = {}
    if os.path.exists(res_path):
        with open(res_path) as f:
            result = json.load(f)
    result["cli_ok"] = bool(cli_ok)
    with open(res_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return 0 if cli_ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("outdir", nargs="?", default="results/train_tiny_e2e")
    ap.add_argument("--steps", type=int, default=2500, help="max train steps")
    ap.add_argument("--no-cli", action="store_true",
                    help="skip the CLI subprocess drive (in-process check only)")
    ap.add_argument("--serve-only", action="store_true",
                    help="skip training; serve an existing outdir/tiny.m "
                         "(e.g. re-drive a CPU-trained model on the TPU)")
    ap.add_argument("--cli-only", action="store_true",
                    help="only the CLI subprocess drive against an existing "
                         "outdir; the parent never touches the backend (the "
                         "single-session relay goes wholly to the child)")
    args = ap.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)

    if args.cli_only:
        return cli_phase(args.outdir)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.models import llama
    from dllama_tpu.models.config import ModelConfig
    from dllama_tpu.quants import blocks
    from dllama_tpu.runtime.train import make_train_step
    from dllama_tpu.tokenizer.bpe import Tokenizer
    from dllama_tpu.formats.tokenizer_file import read_tokenizer

    t_path = os.path.join(args.outdir, "tiny.t")
    m_path = os.path.join(args.outdir, "tiny.m")
    f32_path = os.path.join(args.outdir, "tiny_f32.m")
    build_byte_tokenizer(t_path)
    tokenizer = Tokenizer(read_tokenizer(t_path))

    # Tiny but real Llama: all dims q40-block-aligned (dim, hidden % 32;
    # hidden % 64 so the quantized FFN loads as packed planes, not fallback).
    spec = ModelSpec(
        arch=ArchType.LLAMA, dim=256, hidden_dim=704, n_layers=4,
        n_heads=8, n_kv_heads=4, vocab_size=tokenizer.vocab_size,
        seq_len=256, weights_float_type=blocks.Q40,
    )
    cfg = ModelConfig.from_spec(spec, dtype="float32")

    corpus_ids = tokenizer.encode(CORPUS, add_bos=False)
    bos = tokenizer.bos_id
    print(f"corpus: {len(CORPUS)} chars -> {len(corpus_ids)} byte tokens")

    # Training batches: every T-token window over the wrapped corpus, PLUS a
    # BOS-anchored variant of each (generation feeds BOS + prompt, so BOS
    # must be in-distribution; windows start at every offset, so position
    # can't identify corpus location). T bounds the TRAINED rope positions:
    # generation must stay within prompt+steps <= T or the rollout walks
    # into positions the model has never seen.
    T = 192
    stream = corpus_ids * (2 + (T * 8) // len(corpus_ids))
    windows = []
    for start in range(0, len(corpus_ids)):
        w = stream[start:start + T]
        if len(w) == T:
            windows.append(w)
            windows.append([bos] + w[:-1])
    data = np.asarray(windows, dtype=np.int32)
    print(f"train windows: {data.shape}")

    final_loss, train_s = None, 0.0
    if args.serve_only:
        if not os.path.exists(m_path):
            print(f"--serve-only but {m_path} does not exist")
            return 2
        print(f"serve-only: reusing {m_path}")
    else:
        params = llama.random_params(cfg, seed=0)
        opt = optax.adamw(optax.warmup_cosine_decay_schedule(
            0.0, 3e-3, 50, args.steps, 3e-4), weight_decay=0.01)
        opt_state = opt.init(params)
        step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))

        rng = np.random.default_rng(0)
        B = 8
        t0 = time.perf_counter()
        loss = float("nan")
        for i in range(args.steps):
            batch = data[rng.integers(0, len(data), B)]
            params, opt_state, loss = step(params, opt_state, batch)
            # sync with the device at most every 50 steps: float(loss) blocks
            # on the step; a per-step host round trip serializes the loop
            if i % 50 == 0 or i == args.steps - 1:
                cur = float(loss)
                if i % 100 == 0 or i == args.steps - 1:
                    print(f"step {i:4d}  loss {cur:.4f}")
                if cur < 0.012:
                    print(f"step {i:4d}  loss {cur:.4f} — memorized, stopping")
                    break
        train_s = time.perf_counter() - t0
        final_loss = float(loss)

        # ---- write the trained weights through the real .m writer (Q40) ----
        params = jax.device_get(params)
        tensors = {"token_embedding": np.asarray(params["embedding"], np.float32),
                   "rms_final": np.asarray(params["rms_final"], np.float32),
                   "wcls": np.asarray(params["wcls"], np.float32).T}
        for i in range(spec.n_layers):
            for name in ("wq", "wk", "wv", "wo", "w1", "w2", "w3"):
                tensors[f"layers.{i}.{name}"] = np.asarray(
                    params["layers"][name][i], np.float32).T
            for name in ("rms_att", "rms_ffn"):
                tensors[f"layers.{i}.{name}"] = np.asarray(
                    params["layers"][name][i], np.float32)
        write_model(m_path, spec, {e.name: tensors[e.name].reshape(-1)
                                   for e in tensor_plan(spec)})
        print(f"wrote {m_path} ({os.path.getsize(m_path) / 1e6:.1f} MB q40)")
        # f32 twin for quantization-noise diagnosis (same tensors, F32 file)
        import dataclasses as _dc
        spec_f32 = _dc.replace(spec, weights_float_type=blocks.F32,
                               header_size=0)
        write_model(f32_path, spec_f32,
                    {e.name: tensors[e.name].reshape(-1)
                     for e in tensor_plan(spec_f32)})

    # ---- serve it back through the quantized engine ----
    # Token-level check: the greedy continuation of a corpus prefix must be
    # the corpus suffix. encode() prepends a SentencePiece-style dummy space
    # (like the reference tokenizer), so the prompt/expected split is done on
    # TOKENS of one full-corpus encoding — never by slicing decoded chars.
    n_prompt, n_steps = N_PROMPT, N_STEPS  # rollout stays within trained T
    prompt_ids = [bos] + corpus_ids[:n_prompt]  # BOS + corpus prefix
    expected_ids = corpus_ids[n_prompt:n_prompt + n_steps]
    # byte vocab: corpus_ids = [dummy-space] + one token per corpus char, so
    # token index n maps to CORPUS[n-1]; these strings are what the CLI run
    # feeds/checks (its encode() re-adds the same dummy space)
    prompt = CORPUS[:n_prompt - 1]
    expected = CORPUS[n_prompt - 1:n_prompt - 1 + n_steps]

    from dllama_tpu.formats.weights import WeightFileReader
    from dllama_tpu.runtime.generate import Engine
    from dllama_tpu.runtime.sampler import SamplerConfig

    reader = WeightFileReader(m_path)
    qparams = llama.quant_params_from_reader(reader, cfg)
    engine = Engine(cfg, qparams, SamplerConfig(temperature=0.0))
    toks, prefill_ms, decode_ms = engine.generate_fused(prompt_ids, steps=n_steps)
    completion = tokenizer.decode(list(toks))
    ms_tok = decode_ms / max(1, len(toks) - 1)

    def prefix_match(got, want) -> int:
        n = 0
        for a, b in zip(got, want):
            if a != b:
                break
            n += 1
        return n

    n_match = prefix_match(toks, expected_ids)
    print(f"prompt tail: ...{prompt[-40:]!r}")
    print(f"completion : {completion[:80]!r}")
    print(f"expected   : {expected[:80]!r}")
    print(f"match: {n_match}/{len(expected_ids)} tokens;"
          f" {ms_tok:.2f} ms/token ({1000.0 / ms_tok:.1f} tok/s) on"
          f" {jax.devices()[0].platform}")
    in_process_ok = n_match >= int(0.95 * len(expected_ids))

    if not in_process_ok and os.path.exists(f32_path):
        # q40 noise or underfit? The f32 twin answers.
        with WeightFileReader(f32_path) as r32:
            p32 = llama.params_from_reader(r32, ModelConfig.from_spec(r32.spec))
        e32 = Engine(cfg, p32, SamplerConfig(temperature=0.0))
        t32, _, _ = e32.generate_fused(prompt_ids, steps=n_steps)
        m32 = prefix_match(t32, expected_ids)
        print(f"f32 twin match: {m32}/{len(expected_ids)} tokens — "
              + ("quantization noise is the gap" if m32 > n_match + 10
                 else "underfit, not quantization"))

    # ---- and through the actual CLI, as a user would ----
    cli_ok = None
    if not args.no_cli and jax.default_backend() == "tpu":
        # this parent HOLDS the single relay session; a CLI child would wait
        # for one forever (the r04 rc=124). The battery runs the CLI drive
        # as its own --cli-only stage after this process exits.
        print("on TPU: skipping in-process CLI drive — run "
              f"`python {sys.argv[0]} {args.outdir} --cli-only` next")
        args.no_cli = True
    if not args.no_cli:
        # off-TPU: keep the child off the axon relay (register() blocks
        # while any other process holds the single-session tunnel)
        cli_ok = drive_cli(args.outdir, child_on_cpu=True)

    result = {
        "final_loss": final_loss, "train_seconds": round(train_s, 1),
        "model_bytes": os.path.getsize(m_path),
        "platform": jax.devices()[0].platform,
        "decode_ms_per_token": round(ms_tok, 3),
        "match_tokens": n_match, "expected_tokens": len(expected_ids),
        "in_process_ok": bool(in_process_ok), "cli_ok": cli_ok,
    }
    with open(os.path.join(args.outdir, "e2e_result.json"), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    ok = in_process_ok and (cli_ok is not False)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
