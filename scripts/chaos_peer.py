"""Adversarial TCP peers for the router's event-loop front door.

Three client shapes that punish a threaded data plane and must be
non-events for the selectors one (``dllama_tpu/serving/evloop.py``):

* ``slowloris`` — opens connections and dribbles request-HEADER bytes a
  few at a time, forever. Against a thread-per-connection server each
  peer pins a thread; against the event loop each peer is one idle fd
  that dies at ``--header-timeout``.
* ``midstream_hang`` — starts a real streaming request, reads the first
  bytes of the SSE response, then STOPS READING while holding the
  socket open. The router's bounded relay buffer must pause the
  upstream (structural backpressure) and hard-kill the peer at
  ``--client-stall-timeout`` — without growing RSS in between.
* ``reset`` — sends a partial request then closes with ``SO_LINGER(1, 0)``
  so the kernel emits RST, not FIN: the router sees ECONNRESET at read
  or write time and must tear down one connection's state, nothing else.

Importable (``bench.py``'s BENCH_C10K chaos cohort drives these in
threads — plain BLOCKING sockets on purpose, the chaos lives outside
the loop under test) and runnable standalone::

    python scripts/chaos_peer.py slowloris --port 9900 --peers 50 --duration 10

Each run returns/prints a stats dict; a chaos peer being shed, killed,
or reset is SUCCESS — the one outcome that may never happen is the
router becoming unresponsive to well-behaved traffic, which is the
cohort running next to these in BENCH_C10K.
"""

import argparse
import json
import socket
import struct
import threading
import time

_REQ_HEAD = (b"POST /v1/chat/completions HTTP/1.1\r\n"
             b"Host: chaos\r\n"
             b"Content-Type: application/json\r\n")
_CHAT = (b'{"model": "m", "stream": true, '
         b'"messages": [{"role": "user", "content": "chaos"}]}')


def _connect(host: str, port: int, timeout: float = 5.0):
    try:
        return socket.create_connection((host, port), timeout=timeout)
    except OSError:
        return None  # shed at accept (503 + close) or refused: fine


def slowloris(host: str, port: int, duration_s: float = 10.0,
              drip_interval_s: float = 0.5) -> dict:
    """ONE slow-loris peer: dribble header bytes until the router cuts
    us off or the duration ends. Returns how far we got."""
    stats = {"mode": "slowloris", "bytes_sent": 0, "cut_by_router": False}
    sock = _connect(host, port)
    if sock is None:
        return stats
    deadline = time.monotonic() + duration_s
    body = _REQ_HEAD + b"Content-Length: 10\r\nX-Drip: "
    i = 0
    try:
        while time.monotonic() < deadline:
            # two bytes at a time, never a complete header block
            chunk = body[i % len(body):][:2] or b"aa"
            sock.sendall(chunk)
            stats["bytes_sent"] += len(chunk)
            i += 2
            time.sleep(drip_interval_s)
    except OSError:
        stats["cut_by_router"] = True  # the header deadline did its job
    finally:
        sock.close()
    return stats


def midstream_hang(host: str, port: int, duration_s: float = 10.0,
                   read_bytes: int = 1024) -> dict:
    """ONE hanging-reader peer: start a stream, read a little, then go
    silent with the socket open. A router with bounded relay buffers
    kills us at the client-stall budget; one that buffers unboundedly
    eats the whole stream into RSS instead."""
    stats = {"mode": "midstream_hang", "got_stream": False,
             "killed_by_router": False}
    sock = _connect(host, port)
    if sock is None:
        return stats
    try:
        # a small receive window makes the backpressure bite early
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    except OSError:
        pass
    try:
        sock.sendall(_REQ_HEAD
                     + b"Content-Length: %d\r\n\r\n" % len(_CHAT) + _CHAT)
        sock.settimeout(5.0)
        got = sock.recv(read_bytes)
        stats["got_stream"] = bool(got)
        # ... and now we stop reading. Hold the socket until the router
        # kills it (recv on a dead socket returns b"" / raises) or the
        # duration ends.
        sock.settimeout(duration_s)
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            time.sleep(0.25)
            # poke with a 1-byte WRITE: reading would drain the stalled
            # stream (the thing we refuse to do), and a read-side peek
            # only shows the buffered backlog, never the FIN behind it.
            # A closed connection turns the second poke into
            # EPIPE/ECONNRESET; while alive the pokes are junk trailing
            # the finished request that the router never parses (this
            # connection dies before it could pipeline another).
            try:
                sock.send(b" ")
            except OSError:
                stats["killed_by_router"] = True
                break
    except OSError:
        stats["killed_by_router"] = True
    finally:
        sock.close()
    return stats


def reset(host: str, port: int, after_bytes: int = 40) -> dict:
    """ONE resetting peer: a partial request, then RST (SO_LINGER 1,0).
    The router must see ECONNRESET on one connection and carry on."""
    stats = {"mode": "reset", "sent_rst": False}
    sock = _connect(host, port)
    if sock is None:
        return stats
    try:
        sock.sendall(_REQ_HEAD[:after_bytes])
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
        stats["sent_rst"] = True
    except OSError:
        pass
    finally:
        sock.close()  # with linger(1,0): RST, not FIN
    return stats


MODES = {"slowloris": slowloris, "midstream_hang": midstream_hang,
         "reset": reset}


def run_cohort(mode: str, host: str, port: int, peers: int,
               duration_s: float) -> dict:
    """``peers`` concurrent peers of one mode (each in a thread — these
    are blocking sockets by design), merged stats."""
    fn = MODES[mode]
    results: list = [None] * peers
    kwargs = {} if mode == "reset" else {"duration_s": duration_s}

    def one(i):
        results[i] = fn(host, port, **kwargs)

    threads = [threading.Thread(target=one, args=(i,), daemon=True)
               for i in range(peers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 30.0)
    merged: dict = {"mode": mode, "peers": peers}
    for r in results:
        for k, v in (r or {}).items():
            if isinstance(v, bool):
                merged[k] = merged.get(k, 0) + int(v)
            elif isinstance(v, int):
                merged[k] = merged.get(k, 0) + v
    return merged


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="adversarial peers for the router front door")
    ap.add_argument("mode", choices=sorted(MODES))
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--peers", type=int, default=10)
    ap.add_argument("--duration", type=float, default=10.0)
    args = ap.parse_args(argv)
    out = run_cohort(args.mode, args.host, args.port, args.peers,
                     args.duration)
    print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
