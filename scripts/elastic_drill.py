"""CI elastic drill: every scale transition of the closed-loop fleet
must be invisible to clients — scale-up pre-warms before taking
traffic, scale-down drains gracefully, and a SIGKILL mid-drain still
resolves every live stream byte-identically.

One real fleet of tiny ``cli serve`` subprocesses (synthetic Q40
weights, CPU) behind an IN-PROCESS router, with the autoscale policy
stepped BY HAND (``sup.step()``) so every transition in the drill is
deterministic and attributable.

Part 1 — burst -> policy scale-up with pre-warm. A repeated hot prompt
is pushed through the router (recording it in the router's hot-prompt
index and warming the serving replica's radix cache), then saturating
streams drive pressure to 1.0 until the policy decides UP. The joining
replica must be pre-warmed from its sibling over the kv page stream
(``/v1/prefill`` -> ``/v1/kv/import``) BEFORE activation — gated by
``dllama_prefix_tokens_matched_total`` growing on the NEW replica when
the hot prompt is replayed directly against it, and by zero
``prewarm_fallback`` scale events.

Part 2 — idle -> policy scale-down, client-invisible. With the fleet
idle (one slow live stream riding through the transition), policy steps
must decide DOWN; the victim (the least-loaded replica) drains via
SIGTERM and retires gracefully — the live stream ends 200/[DONE]/
error-free and byte-identical to its unkilled reference, with zero
``drain_killed``.

Part 3 — SIGKILL during drain. Back at two replicas (a second forced
pre-warmed scale-up), a live stream's replica is force-retired and then
SIGKILLed mid-drain. The router's checkpoint + ``/v1/kv/resume``
machinery must splice the stream onto the sibling byte-identically:
``dllama_stream_resume_total{outcome="ok"}`` grows and the kill is
counted as ``drain_killed``.

Zero client-visible errors are required across EVERY request the drill
sends, saturation traffic included.

Artifacts written to --out-dir (uploaded by CI):
    verdict.json                 per-part verdict + counter evidence
    router_metrics.txt           the router's final exposition
    replica-*.log                every replica's (fleet log_dir) output

Usage:  JAX_PLATFORMS=cpu python scripts/elastic_drill.py
            [--out-dir elastic-drill]
Exit 0 only if every gate holds.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import signal
import socket
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCALE_EVENTS = ("joined", "draining", "retired", "spawn_failed",
                "prewarm_fallback", "drain_killed", "injected")


def free_base(span: int) -> int:
    """A base port with ``span`` consecutive free ports above it (the
    fleet binds base..base+n-1 and scale-ups keep counting up)."""
    for _ in range(64):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
        if base + span > 65500:
            continue
        try:
            for i in range(1, span):
                with socket.socket() as t:
                    t.bind(("127.0.0.1", base + i))
            return base
        except OSError:
            continue
    raise RuntimeError("no free port span for the fleet")


def request(port, method, path, body=None, timeout=300, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers=dict({"Content-Type": "application/json"},
                              **(headers or {})))
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def chat(content, max_tokens=48):
    return {"model": "m", "max_tokens": max_tokens, "temperature": 0.0,
            "stream": True,
            "messages": [{"role": "user", "content": content}]}


def sse_parts(data: bytes):
    """-> (content_text, saw_done, error_message-or-None)."""
    text, done, err = [], False, None
    for ev in data.split(b"\n\n"):
        for line in ev.split(b"\n"):
            if not line.startswith(b"data: "):
                continue
            payload = line[6:]
            if payload == b"[DONE]":
                done = True
                continue
            try:
                obj = json.loads(payload)
            except ValueError:
                continue
            if "error" in obj:
                err = obj["error"].get("message")
            for ch in obj.get("choices", []):
                text.append((ch.get("delta") or {}).get("content") or "")
    return "".join(text), done, err


def stream_with_hook(port, body, on_first_content=None):
    """Stream a chat request, invoking ``on_first_content`` as soon as
    the first content delta lands, then reading the stream to its end.
    Returns (status, raw_bytes)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
    try:
        conn.request("POST", "/v1/chat/completions",
                     json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            return resp.status, resp.read()
        buf = b""
        fired = False
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            buf += chunk
            if not fired and on_first_content and b'"content"' in buf:
                fired = True
                on_first_content()
            if buf.endswith(b"data: [DONE]\n\n"):
                break
        return 200, buf
    finally:
        conn.close()


def prefix_matched(port: int) -> float:
    """The replica's dllama_prefix_tokens_matched_total reading."""
    status, data = request(port, "GET", "/metrics", timeout=10)
    if status != 200:
        raise RuntimeError(f"/metrics on :{port} returned {status}")
    for line in data.decode().splitlines():
        if line.startswith("dllama_prefix_tokens_matched_total"):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


HOT = "hot alpha beta"          # part 1's pre-warm refrain
DRAINED = "drain me softly"     # part 2's ride-along stream
CHAOS = "chaos mid drain"       # part 3's resumed stream


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-dir", default="elastic-drill")
    args = ap.parse_args()
    out = os.path.abspath(args.out_dir)
    os.makedirs(out, exist_ok=True)

    import numpy as np

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.tokenizer_file import (TokenizerData,
                                                   write_tokenizer)
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.quants import blocks
    from dllama_tpu.serving import autoscale as asc
    from dllama_tpu.serving import fleet as fleet_mod
    from dllama_tpu.serving import router as router_mod

    art = os.path.join(out, "artifacts")
    os.makedirs(art, exist_ok=True)
    model, tokp = os.path.join(art, "m.m"), os.path.join(art, "t.t")
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=300, seq_len=96,
                     weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    write_model(model, spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(
                    np.float32) for e in tensor_plan(spec)})
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
             + [b"hi"] * 41)
    write_tokenizer(tokp, TokenizerData(
        vocab=vocab, scores=[0.0] * 300, bos_id=1, eos_id=2))

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU children must not register
    #   the axon TPU plugin (single-session tunnel blocks a 2nd registrant)
    # a tiny CPU model streams its tokens in well under a second — slow
    # every SSE frame so streams outlive the scale transitions they gate
    env["DLLAMA_FAULTS"] = "stream:slow:delay_ms=40"

    failures: list = []
    evidence: dict = {}

    fl = fleet_mod.Fleet(
        model, tokp, n_replicas=1, base_port=free_base(4),
        host="127.0.0.1",
        replica_args=["--kv-pages", "16", "--ckpt-interval", "2",
                      "--batch-window", "5", "--batch-max", "2",
                      "--batch-chunk", "2", "--tp", "1"],
        log_dir=out, env=env)
    state = rsrv = None
    try:
        fl.start()
        if not fl.wait_ready(timeout_s=300.0):
            raise RuntimeError("the seed replica never became ready")
        port0 = fl.replicas[0].port
        state = router_mod.RouterState(
            [router_mod.Replica("127.0.0.1", port0)],
            probe_interval_s=0.25, ckpt_interval=2)
        state.probe_once()
        state.start_probes()
        rsrv = router_mod.create_router_server(state, "127.0.0.1", 0)
        r_port = rsrv.server_address[1]
        threading.Thread(target=rsrv.serve_forever, daemon=True).start()
        print(f"fleet up: replica :{port0}, router :{r_port}")

        cfg = asc.PolicyConfig(
            min_replicas=1, max_replicas=2, up_pressure=0.5,
            down_pressure=0.35, up_consecutive=2, down_consecutive=3,
            cooldown_up_s=1.0, cooldown_down_s=2.0)
        sup = fleet_mod.ElasticSupervisor(
            fl, state, asc.AutoscalePolicy(cfg), interval_s=0.2,
            ready_timeout_s=300.0, drain_timeout_s=30.0,
            prewarm_prompts=4, prewarm_tokens=8)

        def events() -> dict:
            return {e: state._m_scale_events.value(event=e)
                    for e in SCALE_EVENTS
                    if state._m_scale_events.value(event=e)}

        def client(res: tuple, what: str):
            """Every drill request is client traffic: 200/[DONE]/no
            error, or the drill fails."""
            status, data = res
            text, done, err = sse_parts(data)
            if status != 200 or err or not done:
                failures.append(f"client-visible damage [{what}]: "
                                f"{status} err={err!r} done={done}")
            return text

        # ---- part 1: burst -> scale-up with pre-warm -----------------
        # compile the seed replica's programs outside every gate below
        client(request(r_port, "POST", "/v1/chat/completions",
                       chat(HOT, max_tokens=8)), "warm-up")
        for i in range(2):  # make HOT the hottest router prompt
            client(request(r_port, "POST", "/v1/chat/completions",
                           chat(HOT, max_tokens=8)), f"hot-{i}")

        stop_sat = threading.Event()

        def saturate(i):
            while not stop_sat.is_set():
                client(request(r_port, "POST", "/v1/chat/completions",
                               chat(HOT, max_tokens=48)), f"sat-{i}")

        sats = [threading.Thread(target=saturate, args=(i,), daemon=True)
                for i in range(4)]
        for t in sats:
            t.start()
        ups0 = state._m_policy_evals.value(decision="up")
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and sup.n_replicas() < 2:
            sup.step()
            time.sleep(0.2)
        stop_sat.set()
        for t in sats:
            t.join(timeout=300.0)
        evidence["part1_events"] = events()
        ups = state._m_policy_evals.value(decision="up") - ups0
        if sup.n_replicas() < 2:
            failures.append("the policy never scaled up under a "
                            f"saturating burst (up decisions {ups:.0f})")
            raise RuntimeError("part 1 failed, nothing left to drill")
        if ups < 1:
            failures.append("scaled up without an up decision (policy "
                            "bypassed?)")
        if events().get("prewarm_fallback"):
            failures.append("scale-up fell back to a cold join: "
                            f"{events()}")
        new = [r for r in fl.replicas if r.port != port0][0]
        matched0 = prefix_matched(new.port)
        # the hot prompt DIRECTLY against the new replica: its radix
        # must already hold the prompt pages from the pre-warm import.
        # Batch class on purpose — a lone interactive completion is
        # served on the solo engine path, which never consults the
        # paged pool's radix cache and would read delta 0 even on a
        # perfectly warmed replica
        client(request(new.port, "POST", "/v1/chat/completions",
                       chat(HOT, max_tokens=8),
                       headers={"X-Dllama-Class": "batch"}),
               "prewarm-probe")
        delta = prefix_matched(new.port) - matched0
        evidence["part1_prefix_tokens_matched_delta"] = delta
        evidence["part1_up_decisions"] = ups
        if delta <= 0:
            failures.append(
                "the joining replica was not pre-warmed: replaying the "
                "hot prompt against it matched "
                f"{delta:.0f} prefix tokens (expected > 0)")
        print(f"part 1 done: fleet=2, up decisions {ups:.0f}, "
              f"pre-warm prefix delta {delta:.0f}, events {events()}")

        # ---- part 2: idle -> policy scale-down, client-invisible -----
        ref2 = client(request(r_port, "POST", "/v1/chat/completions",
                              chat(DRAINED, max_tokens=48)), "part2-ref")
        downs0 = state._m_policy_evals.value(decision="down")
        dk0 = state._m_scale_events.value(event="drain_killed")
        live2 = [None]

        def ride2():
            live2[0] = request(r_port, "POST", "/v1/chat/completions",
                               chat(DRAINED, max_tokens=48))

        rt2 = threading.Thread(target=ride2, daemon=True)
        rt2.start()
        # step the policy while the stream rides: one slow stream on a
        # 2-replica fleet sits under down_pressure, so the cold streak
        # plus the post-part-1 cooldown must decide DOWN and retire the
        # LEAST-loaded replica out from under the fleet without the
        # client noticing
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and sup.n_replicas() > 1:
            sup.step()
            time.sleep(0.2)
        rt2.join(timeout=300.0)
        downs = state._m_policy_evals.value(decision="down") - downs0
        evidence["part2_down_decisions"] = downs
        evidence["part2_events"] = events()
        if sup.n_replicas() != 1:
            failures.append("the policy never scaled down an idle fleet "
                            f"(down decisions {downs:.0f})")
        if downs < 1:
            failures.append("scaled down without a down decision")
        if state._m_scale_events.value(event="drain_killed") != dk0:
            failures.append("an idle graceful drain needed SIGKILL")
        got2 = client(live2[0], "part2-live") if live2[0] else ""
        if live2[0] is None:
            failures.append("part 2 live stream never resolved")
        elif got2 != ref2:
            failures.append(f"stream across graceful scale-down != "
                            f"reference: {got2!r} != {ref2!r}")
        print(f"part 2 done: fleet=1, down decisions {downs:.0f}, "
              f"events {events()}")

        # ---- part 3: SIGKILL during drain ----------------------------
        if not sup.scale_up():  # forced: re-exercises the pre-warm path
            raise RuntimeError("forced scale-up for part 3 failed")
        ref3 = client(request(r_port, "POST", "/v1/chat/completions",
                              chat(CHAOS, max_tokens=48)), "part3-ref")
        ok0 = state._m_resumes.value(outcome="ok")
        dk0 = state._m_scale_events.value(event="drain_killed")

        def kill_mid_drain():
            time.sleep(0.1)  # let a checkpoint frame or two land first
            victim = None
            for rep in state.replicas:
                if rep.snapshot().get("inflight", 0) > 0:
                    victim = rep.name
                    break
            if victim is None:
                failures.append("part 3: no in-flight replica found")
                return
            evidence["part3_victim"] = victim
            proc = next(p for p in fl.replicas if p.name == victim)
            threading.Thread(target=lambda: sup.scale_down(target=victim),
                             daemon=True).start()
            time.sleep(0.3)  # SIGTERM delivered, the drain is under way
            if proc.proc.poll() is None:
                os.kill(proc.proc.pid, signal.SIGKILL)
                print(f"part 3: SIGKILLed {victim} mid-drain")

        status3, data3 = stream_with_hook(r_port, chat(CHAOS, max_tokens=48),
                                          on_first_content=kill_mid_drain)
        got3 = client((status3, data3), "part3-live")
        resumes = state._m_resumes.value(outcome="ok") - ok0
        drain_killed = state._m_scale_events.value(event="drain_killed") - dk0
        evidence["part3_resumes_ok"] = resumes
        evidence["part3_events"] = events()
        if got3 != ref3:
            kind = ("duplicate bytes" if ref3 in got3
                    else "missing bytes" if got3 in ref3
                    else "diverged bytes")
            failures.append(f"stream across SIGKILL-mid-drain != "
                            f"reference ({kind}): {got3!r} != {ref3!r}")
        if resumes < 1:
            failures.append("mid-drain SIGKILL but no ok resume counted")
        if drain_killed < 1:
            failures.append("mid-drain SIGKILL not counted drain_killed")
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and state._count_registered() > 1:
            time.sleep(0.1)
        if state._count_registered() != 1:
            failures.append("the killed replica was never deregistered")
        print(f"part 3 done: resumes ok {resumes:.0f}, "
              f"drain_killed {drain_killed:.0f}, events {events()}")
        with open(os.path.join(out, "router_metrics.txt"), "w") as f:
            f.write(state.metrics.render())
    except Exception as e:
        failures.append(f"drill aborted: {e!r}")
    finally:
        if state is not None:
            state.stop_probes()
        if rsrv is not None:
            rsrv.shutdown()
        fl.drain(timeout_s=30.0)

    verdict = {"ok": not failures, "failures": failures,
               "evidence": evidence}
    with open(os.path.join(out, "verdict.json"), "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        return 1
    print("elastic drill: pre-warmed scale-up, client-invisible "
          "scale-down, and byte-identical resume across a SIGKILLed "
          "drain all verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
