#!/bin/bash
# Post-kernel-landing measurement battery (round 4, second pass): re-measures
# the headline benches with the q40 no-subtract kernel as the default, the
# kernel-variant shootout including the shipped C/stacked variants, the fixed
# (traced-args) ablation, and the e2e drives the first battery lost to the
# wedged tunnel. Same conventions as measure_all.sh: per-command hard
# timeouts, every result banked separately under results/.
#
#   bash scripts/measure_r04b.sh [results_dir]
set -u
OUT=${1:-results}
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%S)
log() { echo "== $* ($(date -u +%H:%M:%S))" | tee -a "$OUT/measure_$STAMP.log"; }

# The relay serves ONE session and wedges for a while after a client dies
# (the first r04 battery lost kernel_bench + native_e2e to 1500 s timeouts
# against a wedged relay). Probe before every stage; while the probe fails,
# wait instead of letting the stage burn its timeout doing nothing.
probe_tunnel() {
  timeout -k 10 150 python -c '
import time, jax, jax.numpy as jnp
t0 = time.time()
jax.block_until_ready(jnp.ones((256, 256), jnp.bfloat16) @ jnp.ones((256, 256), jnp.bfloat16))
print(f"TUNNEL_OK {time.time()-t0:.1f}s")' 2>&1 | grep -q TUNNEL_OK
}
# worst case per call: 8 probes x (150 s timeout + 240 s sleep) ~= 52 min —
# but only the FIRST stage ever pays it: once a wait exhausts, TUNNEL_DEAD
# short-circuits every later stage so a dead tunnel can't stall the battery
# for hours. The long inter-probe sleep also gives the single-session relay
# a client-death-free window to recover in (each timed-out probe is itself
# a dying client, which is what wedges the relay in the first place).
TUNNEL_DEAD=0
wait_tunnel() {
  local i
  [ "$TUNNEL_DEAD" = 1 ] && return 1
  for i in $(seq 1 8); do
    probe_tunnel && return 0
    log "tunnel not answering (probe $i/8), waiting"
    [ "$i" -lt 8 ] && sleep 240
  done
  TUNNEL_DEAD=1
  return 1
}

run() {
  local name=$1; shift
  if ! wait_tunnel; then
    log "$name SKIPPED: tunnel never answered"
    return
  fi
  log "$name: $*"
  local T=${CMD_TIMEOUT:-1500}
  timeout -k 30 "$T" "$@" >"$OUT/${name}_$STAMP.out" 2>&1
  local rc=$?
  { [ $rc -eq 124 ] || [ $rc -eq 137 ]; } && log "$name TIMED OUT after ${T}s (rc=$rc)"
  log "$name rc=$rc"
  tail -3 "$OUT/${name}_$STAMP.out" | tee -a "$OUT/measure_$STAMP.log"
}

# headline first: the end-to-end effect of the nosub kernel
CMD_TIMEOUT=900 run bench_7b_nosub env BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_8b_nosub env BENCH_MODEL=llama3 BENCH_DEADLINE_S=840 python bench.py
# prefill throughput (the reference prefills at full decode cost per token)
CMD_TIMEOUT=900 run bench_7b_prefill env BENCH_PREFILL=448 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_8b_prefill env BENCH_MODEL=llama3 BENCH_PREFILL=448 BENCH_DEADLINE_S=840 python bench.py
# long-context decode: full-cache masked attention at seq 4096, bf16 vs f8
# KV (f8 halves exactly the bytes the longer context adds)
CMD_TIMEOUT=900 run bench_7b_seq4k env BENCH_SEQ=4096 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_seq4k_f8 env BENCH_SEQ=4096 BENCH_CACHE=f8 BENCH_DEADLINE_S=840 python bench.py
# flash-decode: live-prefix-only cache reads (ops/flash_decode.py) — the
# seq-4k A/B is the payoff case, the stock run checks for regression
CMD_TIMEOUT=900 run bench_7b_seq4k_flash env BENCH_SEQ=4096 DLLAMA_FLASH_DECODE=1 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_flash env DLLAMA_FLASH_DECODE=1 BENCH_DEADLINE_S=840 python bench.py
# batched serving at long context: per-row live-prefix reads vs full slabs
CMD_TIMEOUT=900 run bench_7b_batch8_seq1k env BENCH_BATCH=8 BENCH_SEQ=1024 BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_7b_batch8_seq1k_flash env BENCH_BATCH=8 BENCH_SEQ=1024 DLLAMA_FLASH_DECODE=1 BENCH_DEADLINE_S=840 python bench.py
# the A/B that justifies (or reverts) the default: flat + stacked variants
run qkernel_r04b python scripts/qkernel_experiments.py all
# where the remaining ms go, with the traced-args fix
run ablate_r04b python scripts/ablate_decode.py
# kernel reference points (first battery lost this stage to the wedge)
run kernel_bench_r04b python scripts/kernel_bench.py
CMD_TIMEOUT=900 run bench_tiny_nosub env BENCH_MODEL=tiny BENCH_DEADLINE_S=840 python bench.py
CMD_TIMEOUT=900 run bench_moe_nosub env BENCH_MODEL=moe BENCH_DEADLINE_S=840 python bench.py
# Grok-1-shape MoE (the reference's flagship arch: scales, post-norms, GELU)
CMD_TIMEOUT=900 run bench_grok env BENCH_MODEL=grok BENCH_DEADLINE_S=840 python bench.py
# native runtime end to end (exports, builds, drives dllama-native)
run native_e2e_r04b python scripts/native_e2e.py /tmp/dllama_native_e2e_$STAMP
# the real-trained-checkpoint artifact: train on the TPU, write a .m file,
# serve it back through the quantized engine AND the CLI, check the text
run train_e2e_r04b python scripts/train_tiny_e2e.py results/train_tiny_e2e_r04b

log "r04b battery done — results in $OUT/"
