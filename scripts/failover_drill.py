"""CI failover drill: a decode replica dying mid-stream must be
invisible to the client, and every fallback-matrix row must terminate
cleanly.

Part 1 — the real fleet. Two "both" ``cli serve`` subprocesses (tiny
synthetic weights, CPU) behind an IN-PROCESS router with checkpointing
on. A streamed chat request runs once unkilled (the reference), then
again with the SERVING replica SIGKILLed right after its first content
delta. The client must still get HTTP 200, a ``[DONE]``, no error
event, and byte-identical assembled content — zero duplicate and zero
missing bytes across the splice — with the router's
``dllama_stream_resume_total{outcome="ok"}`` counter showing exactly
the one resume. Both replicas are warmed DIRECTLY (not through the
router, whose affinity would park every warm-up on one sibling), so the
survivor's radix cache holds the prompt pages when the resume lands —
and the drill GATES that ``/v1/kv/resume`` aliased them instead of
re-prefilling: ``dllama_prefix_tokens_matched_total`` must grow on the
surviving replica across the resume.

Part 2 — the fallback matrix. Two IN-PROCESS replica servers (so
``DLLAMA_FAULTS``-style plans installed via :mod:`dllama_tpu.faults`
reach both the replicas' ``stream``/``ckpt_write``/``kv_import`` seams
and the router's ``resume`` seam) stage every non-ok outcome:

    injected      resume:raise at the decision point
    no_ckpt       ckpt_write:raise — no checkpoint ever shipped
    stale_ckpt    stored splice offset tampered ahead of the stream
    admit_failed  kv_import:raise — every sibling refuses the snapshot
    no_replica    single-replica fleet, nobody left to resume on
    exhausted     stream:raise,times=2 — the resumed stream dies too

Every leg must end with HTTP 200, a typed SSE ``error`` event, a
terminating ``[DONE]``, and exactly one increment of the expected
outcome — a torn TCP cut in any leg fails the drill.

Artifacts written to --out-dir (uploaded by CI):
    verdict.json                 per-leg verdict + counter evidence
    router_metrics.txt           the part-1 router's exposition
    replica-0.log / replica-1.log

Usage:  JAX_PLATFORMS=cpu python scripts/failover_drill.py
            [--out-dir failover-drill]
Exit 0 only if every leg holds.
"""

from __future__ import annotations

import argparse
import base64
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RESUME_OUTCOMES = ("ok", "no_ckpt", "stale_ckpt", "admit_failed",
                   "no_replica", "injected", "exhausted")


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def request(port, method, path, body=None, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def chat(max_tokens=48, **kw):
    body = {"model": "m", "max_tokens": max_tokens, "temperature": 0.0,
            "stream": True,
            "messages": [{"role": "user", "content": "hi hi resume me"}]}
    body.update(kw)
    return body


def sse_parts(data: bytes):
    """-> (content_text, saw_done, error_message-or-None)."""
    text, done, err = [], False, None
    for ev in data.split(b"\n\n"):
        for line in ev.split(b"\n"):
            if not line.startswith(b"data: "):
                continue
            payload = line[6:]
            if payload == b"[DONE]":
                done = True
                continue
            try:
                obj = json.loads(payload)
            except ValueError:
                continue
            if "error" in obj:
                err = obj["error"].get("message")
            for ch in obj.get("choices", []):
                text.append((ch.get("delta") or {}).get("content") or "")
    return "".join(text), done, err


def wait_ready(port: int, proc, deadline_s: float = 300.0) -> None:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica :{port} exited {proc.returncode} before ready")
        try:
            status, _ = request(port, "GET", "/ready", timeout=2)
            if status == 200:
                return
        except OSError:
            pass  # not listening yet
        time.sleep(0.5)
    raise RuntimeError(f"replica :{port} never became ready")


def stream_with_kill(port, body, on_first_content=None):
    """Stream a chat request, invoking ``on_first_content`` (e.g. the
    SIGKILL) as soon as the first content delta lands, then reading the
    stream to its end. Returns (status, raw_bytes)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
    try:
        conn.request("POST", "/v1/chat/completions",
                     json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            return resp.status, resp.read()
        buf = b""
        fired = False
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            buf += chunk
            if not fired and on_first_content and b'"content"' in buf:
                fired = True
                on_first_content()
            if buf.endswith(b"data: [DONE]\n\n"):
                break
        return 200, buf
    finally:
        conn.close()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-dir", default="failover-drill")
    args = ap.parse_args()
    out = os.path.abspath(args.out_dir)
    os.makedirs(out, exist_ok=True)

    import numpy as np

    from dllama_tpu import faults
    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.tokenizer_file import (TokenizerData,
                                                   write_tokenizer)
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.quants import blocks
    from dllama_tpu.serving import router as router_mod

    art = os.path.join(out, "artifacts")
    os.makedirs(art, exist_ok=True)
    model, tokp = os.path.join(art, "m.m"), os.path.join(art, "t.t")
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=300, seq_len=96,
                     weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    write_model(model, spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(
                    np.float32) for e in tensor_plan(spec)})
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
             + [b"hi"] * 41)
    write_tokenizer(tokp, TokenizerData(
        vocab=vocab, scores=[0.0] * 300, bos_id=1, eos_id=2))

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU children must not register
    #   the axon TPU plugin (single-session tunnel blocks a 2nd registrant)
    env.pop("DLLAMA_FAULTS", None)

    def spawn(idx: int, port: int):
        log = open(os.path.join(out, f"replica-{idx}.log"), "w")
        # a tiny CPU model streams 48 tokens in well under a second —
        # slow every SSE frame write so the SIGKILL lands squarely
        # inside a live stream, not after its [DONE]
        proc = subprocess.Popen(
            [sys.executable, "-m", "dllama_tpu.cli", "serve",
             "--model", model, "--tokenizer", tokp,
             "--host", "127.0.0.1", "--port", str(port),
             "--role", "both", "--kv-pages", "16", "--ckpt-interval", "2",
             "--batch-window", "5", "--batch-max", "2", "--batch-chunk", "2",
             "--tp", "1"],
            env=dict(env, DLLAMA_FAULTS="stream:slow:delay_ms=40"),
            cwd=REPO,
            stdout=log, stderr=subprocess.STDOUT, start_new_session=True)
        log.close()
        return proc

    failures = []
    evidence: dict = {}

    def resume_counts(st) -> dict:
        return {o: st._m_resumes.value(outcome=o) for o in RESUME_OUTCOMES
                if st._m_resumes.value(outcome=o)}

    def prefix_matched(port: int) -> float:
        """The replica's dllama_prefix_tokens_matched_total reading."""
        status, data = request(port, "GET", "/metrics", timeout=10)
        if status != 200:
            raise RuntimeError(f"/metrics on :{port} returned {status}")
        for line in data.decode().splitlines():
            if line.startswith("dllama_prefix_tokens_matched_total"):
                return float(line.rsplit(" ", 1)[1])
        return 0.0

    # ---- part 1: the real fleet, a real SIGKILL ----------------------
    ports = [free_port(), free_port()]
    procs = [spawn(i, p) for i, p in enumerate(ports)]
    state = None
    rsrv = None
    try:
        for p, proc in zip(ports, procs):
            wait_ready(p, proc)
        print(f"replicas up: :{ports[0]}  :{ports[1]}")

        state = router_mod.RouterState(
            [router_mod.Replica("127.0.0.1", p) for p in ports],
            probe_interval_s=0.3, ckpt_interval=2)
        state.probe_once()
        state.start_probes()
        rsrv = router_mod.create_router_server(state, host="127.0.0.1",
                                               port=0)
        r_port = rsrv.server_address[1]
        threading.Thread(target=rsrv.serve_forever, daemon=True).start()
        print(f"router up: :{r_port} (ckpt interval {state.ckpt_interval})")

        # warm each replica DIRECTLY — the router's affinity would park
        # both warm-ups on one sibling. This compiles both programs (so
        # compile time doesn't stretch the killed stream's token cadence)
        # AND leaves the prompt pages warm in each replica's radix cache,
        # so the resume leg below can gate the skipped re-prefill.
        for p in ports:
            status, _ = request(p, "POST", "/v1/chat/completions", chat())
            if status != 200:
                raise RuntimeError(f"warm-up on :{p} returned {status}")
        # reference: the SAME streamed request, nobody killed
        status, data = request(r_port, "POST", "/v1/chat/completions",
                               chat())
        if status != 200:
            raise RuntimeError(f"reference stream returned {status}")
        ref_text, ref_done, ref_err = sse_parts(data)
        if not ref_done or ref_err or not ref_text:
            raise RuntimeError(
                f"reference stream malformed: done={ref_done} "
                f"err={ref_err!r} len={len(ref_text)}")
        if b"dllama-ckpt" in data:
            failures.append("checkpoint control frame leaked to the client")

        def kill_serving():
            # the router state is in-process: the replica with a live
            # stream is the one with nonzero in-flight
            time.sleep(0.1)  # let a checkpoint frame or two land first
            for i, r in enumerate(state.replicas):
                if r.snapshot().get("inflight", 0) > 0:
                    os.kill(procs[i].pid, signal.SIGKILL)
                    evidence["killed_replica"] = f"127.0.0.1:{ports[i]}"
                    print(f"SIGKILLed serving replica :{ports[i]} "
                          "mid-stream")
                    return
            failures.append("no in-flight replica found to kill")

        matched0 = {p: prefix_matched(p) for p in ports}
        status, data = stream_with_kill(r_port, chat(),
                                        on_first_content=kill_serving)
        got_text, got_done, got_err = sse_parts(data)
        evidence["part1_resume_counters"] = resume_counts(state)
        evidence["part1_content_len"] = len(got_text)
        # the skipped re-prefill, GATED: /v1/kv/resume on the survivor
        # must have aliased the warm prompt pages out of its radix cache
        # (the warm-ups above put them there), not re-imported or
        # re-prefilled them
        killed = evidence.get("killed_replica", "")
        survivors = [p for p in ports if not killed.endswith(f":{p}")]
        if killed and len(survivors) == 1:
            delta = prefix_matched(survivors[0]) - matched0[survivors[0]]
            evidence["part1_prefix_tokens_matched_delta"] = delta
            if delta <= 0:
                failures.append(
                    "resume re-prefilled a warm prompt: "
                    "dllama_prefix_tokens_matched_total grew by "
                    f"{delta:.0f} on surviving replica :{survivors[0]}")
        if status != 200:
            failures.append(f"killed stream returned {status}")
        if not got_done:
            failures.append("killed stream ended without [DONE] "
                            "(torn TCP cut, not a clean stream)")
        if got_err:
            failures.append(f"killed stream carried an error event: "
                            f"{got_err!r}")
        if got_text != ref_text:
            # diagnose dup vs gap for the verdict
            kind = ("duplicate bytes" if ref_text in got_text
                    else "missing bytes" if got_text in ref_text
                    else "diverged bytes")
            failures.append(
                f"killed stream content != reference ({kind}): "
                f"{got_text!r} != {ref_text!r}")
        if state._m_resumes.value(outcome="ok") < 1:
            failures.append(
                "no ok resume counted: "
                f"{resume_counts(state)}")
        with open(os.path.join(out, "router_metrics.txt"), "w") as f:
            f.write(state.metrics.render())
        print(f"part 1 done: resumes {resume_counts(state)}")
    except Exception as e:
        failures.append(f"part 1 aborted: {e!r}")
    finally:
        if state is not None:
            state.stop_probes()
        if rsrv is not None:
            rsrv.shutdown()
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()

    # ---- part 2: every fallback-matrix row, via fault injection ------
    try:
        from dllama_tpu.formats.tokenizer_file import TokenizerData as TD
        from dllama_tpu.models import llama
        from dllama_tpu.models.config import ModelConfig
        from dllama_tpu.runtime.generate import Engine
        from dllama_tpu.runtime.sampler import SamplerConfig
        from dllama_tpu.serving.api_server import ServerState, create_server
        from dllama_tpu.tokenizer.bpe import Tokenizer

        tok = Tokenizer(TD(
            vocab=[b"<unk>", b"<s>", b"</s>"]
                  + [b"<0x%02X>" % b for b in range(256)],
            scores=[0.0] * 259, bos_id=1, eos_id=2))
        cfg = ModelConfig(arch="llama", dim=64, hidden_dim=128, n_layers=2,
                          n_heads=4, n_kv_heads=2,
                          vocab_size=tok.vocab_size, seq_len=128,
                          head_size=16, kv_dim=32, dtype="float32")
        params = llama.random_params(cfg, seed=13)

        def mk_server():
            engine = Engine(cfg, params,
                            SamplerConfig(temperature=0.0, seed=1))
            st = ServerState(engine, tok, cfg, model_name="tiny",
                             template="llama3", batch_window_ms=5.0,
                             batch_chunk=2, kv_pages=16, ckpt_interval=2)
            srv = create_server(st, host="127.0.0.1", port=0)
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            return srv, srv.server_address[1]

        srvA, pA = mk_server()
        srvB, pB = mk_server()
        servers = [srvA, srvB]

        def leg(name, outcome, plan, replicas, tamper=None):
            st = router_mod.RouterState(
                [router_mod.Replica("127.0.0.1", p) for p in replicas],
                probe_interval_s=60.0, ckpt_interval=2)
            st.probe_once()
            if tamper:
                tamper(st)
            rs = router_mod.create_router_server(st, "127.0.0.1", 0)
            threading.Thread(target=rs.serve_forever, daemon=True).start()
            try:
                faults.install(plan)
                status, data = request(rs.server_address[1], "POST",
                                       "/v1/chat/completions",
                                       chat(max_tokens=12))
            finally:
                faults.clear()
                rs.shutdown()
            _, done, err = sse_parts(data)
            counts = resume_counts(st)
            evidence[f"leg_{name}"] = {"status": status, "done": done,
                                       "error": err, "resumes": counts}
            if status != 200:
                failures.append(f"[{name}] returned {status}")
            if name != "ok" and err is None:
                failures.append(f"[{name}] no SSE error event "
                                "(silent termination)")
            if not done:
                failures.append(f"[{name}] stream ended without [DONE]")
            if counts.get(outcome, 0) != 1:
                failures.append(
                    f"[{name}] expected one {outcome!r} resume, "
                    f"got {counts}")
            print(f"leg {name}: {counts} error={err!r}")

        death = "stream:raise:after=4,times=1"

        def stale_put(st):
            real = st.ckpt_store.put

            def put(rid, payload, offset, replica):
                real(rid, payload, offset + 10**9, replica)
            st.ckpt_store.put = put

        leg("injected", "injected", death + ";resume:raise:times=1",
            [pA, pB])
        leg("no_ckpt", "no_ckpt", death + ";ckpt_write:raise", [pA, pB])
        leg("stale_ckpt", "stale_ckpt", death, [pA, pB], tamper=stale_put)
        leg("admit_failed", "admit_failed", death + ";kv_import:raise",
            [pA, pB])
        leg("no_replica", "no_replica", death, [pA])
        leg("exhausted", "exhausted", "stream:raise:after=4,times=2",
            [pA, pB])
        for srv in servers:
            srv.shutdown()
    except Exception as e:
        failures.append(f"part 2 aborted: {e!r}")

    verdict = {"ok": not failures, "failures": failures,
               "evidence": evidence}
    with open(os.path.join(out, "verdict.json"), "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        return 1
    print("failover drill: bit-identical resume after SIGKILL + every "
          "fallback-matrix row terminating cleanly all verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
