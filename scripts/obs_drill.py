"""CI observability fault drill: faults must move counters.

Boots the tiny synthetic server in-process, scrapes /metrics, then fires
one fault of each class the chaos suite knows — queue overflow (429),
scheduler crash (503), poisoned logits (quarantine, 500), deadline expiry
(504) — and scrapes again. The drill PASSES only if every injected fault
produced a nonzero counter delta: an outage class with no metric movement
is an outage an operator cannot alert on, and that is the regression this
lane exists to catch.

Artifacts written to --out-dir (uploaded by CI):
    metrics_before.txt / metrics_after.txt   raw Prometheus expositions
    deltas.json                              per-counter deltas + verdict
    trace.jsonl                              Chrome/Perfetto request spans
    requests.jsonl                           structured JSON request logs

Usage:  JAX_PLATFORMS=cpu python scripts/obs_drill.py [--out-dir obs-drill]
Exit 0 only if every fault class moved its counter.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# counter -> the fault class whose visibility it proves
WATCHED = {
    "dllama_admission_rejections_total": "queue overflow (429)",
    "dllama_scheduler_crashes_total": "scheduler crash (503)",
    "dllama_numeric_quarantines_total": "poisoned logits (quarantine)",
    "dllama_deadline_expirations_total": "deadline expiry (504)",
    "dllama_http_requests_total": "request accounting",
}


def parse_exposition(text: str) -> dict:
    """Family name -> summed value across its series (labels collapsed:
    the drill asserts movement, not attribution)."""
    totals: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        sample, _, value = line.rpartition(" ")
        name = sample.partition("{")[0]
        # fold histogram series into their family's count
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
                break
        try:
            totals[name] = totals.get(name, 0.0) + float(value)
        except ValueError:
            pass
    return totals


def request(port, method, path, body=None, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def chat(**kw):
    body = {"model": "drill", "max_tokens": 8, "temperature": 0.0,
            "messages": [{"role": "user", "content": "observability drill"}]}
    body.update(kw)
    return body


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-dir", default="obs-drill")
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    from dllama_tpu import faults, observability
    from dllama_tpu.models import llama
    from dllama_tpu.runtime.generate import Engine
    from dllama_tpu.runtime.sampler import SamplerConfig
    from dllama_tpu.serving.api_server import ServerState, create_server
    from tests.test_api_server import make_tokenizer
    from tests.test_llama_forward import tiny_cfg

    observability.configure_trace(os.path.join(args.out_dir, "trace.jsonl"))
    log_stream = open(os.path.join(args.out_dir, "requests.jsonl"), "w")

    tok = make_tokenizer()
    cfg = tiny_cfg(vocab_size=tok.vocab_size, seq_len=512, dim=32, kv_dim=16,
                   head_size=8, hidden_dim=64)
    params = llama.random_params(cfg, seed=13)
    engine = Engine(cfg, params, SamplerConfig(temperature=0.0, seed=1))
    state = ServerState(engine, tok, cfg, model_name="drill",
                        template="llama3", batch_window_ms=5.0, batch_max=4,
                        queue_depth=4, log_json=True, log_stream=log_stream)
    srv = create_server(state, host="127.0.0.1", port=0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def scrape(fname: str) -> dict:
        status, data = request(port, "GET", "/metrics", timeout=30)
        assert status == 200, f"/metrics returned {status}"
        text = data.decode()
        with open(os.path.join(args.out_dir, fname), "w") as f:
            f.write(text)
        return parse_exposition(text)

    def expect(label: str, want: int, got: int) -> None:
        ok = "ok" if got == want else f"UNEXPECTED (wanted {want})"
        print(f"  {label}: HTTP {got} [{ok}]")

    try:
        # warm-up: one healthy request so latency series exist
        status, _ = request(port, "POST", "/v1/chat/completions", chat())
        expect("healthy request", 200, status)
        before = scrape("metrics_before.txt")

        print("firing fault classes:")
        # queue overflow -> 429
        tickets = [state.gate.acquire() for _ in range(4)]
        try:
            status, _ = request(port, "POST", "/v1/chat/completions", chat(),
                                timeout=30)
            expect("queue overflow", 429, status)
        finally:
            for t in tickets:
                state.gate.release(t)

        # scheduler crash -> 503 (supervisor restarts it)
        faults.install("scheduler:raise:times=1")
        status, _ = request(port, "POST", "/v1/chat/completions", chat())
        faults.clear()
        expect("scheduler crash", 503, status)

        # poisoned logits -> numeric quarantine -> 500
        faults.install("logits:nan:after=2")
        status, _ = request(port, "POST", "/v1/chat/completions", chat())
        faults.clear()
        expect("poisoned logits", 500, status)

        # deadline expiry -> 504
        state.request_timeout = 0.0001
        status, _ = request(port, "POST", "/v1/chat/completions",
                            chat(max_tokens=32))
        state.request_timeout = 0.0
        expect("deadline expiry", 504, status)

        # prove the server still serves after the whole gauntlet
        status, _ = request(port, "POST", "/v1/chat/completions", chat())
        expect("post-gauntlet request", 200, status)

        after = scrape("metrics_after.txt")
    finally:
        srv.shutdown()
        observability.configure_trace(None)
        log_stream.close()

    deltas = {name: after.get(name, 0.0) - before.get(name, 0.0)
              for name in WATCHED}
    failures = [f"{name} ({why}) did not move"
                for name, why in WATCHED.items() if deltas[name] <= 0]

    trace_file = os.path.join(args.out_dir, "trace.jsonl")
    raw = open(trace_file).read()
    events = [json.loads(l.rstrip(","))
              for l in raw.splitlines()[1:] if l.strip()]
    n_requests = sum(1 for e in events if e.get("name") == "request")
    if not raw.startswith("[\n") or n_requests < 5:
        failures.append(
            f"trace.jsonl malformed or sparse ({n_requests} request spans)")

    verdict = {"ok": not failures, "deltas": deltas, "failures": failures,
               "trace_request_spans": n_requests}
    with open(os.path.join(args.out_dir, "deltas.json"), "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)

    print("\ncounter deltas:")
    for name, d in sorted(deltas.items()):
        print(f"  {name}: +{d:g}")
    print(f"trace spans: {n_requests} requests -> {trace_file}")
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        return 1
    print("observability drill: every fault class moved a counter")
    return 0


if __name__ == "__main__":
    sys.exit(main())
