"""CI observability fault drill: faults must move counters.

Boots the tiny synthetic server in-process, scrapes /metrics, then fires
one fault of each class the chaos suite knows — queue overflow (429),
scheduler crash (503), poisoned logits (quarantine, 500), deadline expiry
(504) — and scrapes again. The drill PASSES only if every injected fault
produced a nonzero counter delta: an outage class with no metric movement
is an outage an operator cannot alert on, and that is the regression this
lane exists to catch.

``--fleet`` runs the FLEET leg instead: a real ``cli fleet`` subprocess
topology (router + 2 replicas, tiny synthetic weights, CPU) with tracing,
the flight recorder, the time-series sampler and a microsecond
interactive TTFT SLO target armed. It passes only if (a) the merged
Perfetto file contains at least one STITCHED request — a router proxy
span and a replica request span sharing the request id, tied by a flow
arrow — with the router and each replica on distinct named process
tracks, (b) the router's /metrics/fleet chat-route counter sums equal
the per-replica /metrics sums, (c) the SIGTERM drain left one
flight-recorder dump per process whose ring holds the drilled request
ids, (d) the drilled chats breach the TTFT SLO so the federated /alerts
flips to FIRING (transition flight-recorded) and then back to RESOLVED
once the burst ages out of both burn windows, (e) the federated
/metrics/history window is non-empty for the router and every replica,
and (f) ``cli explain`` joins a drilled request into a waterfall whose
phase sum is within tolerance of the measured wall time.

Artifacts written to --out-dir (uploaded by CI):
    metrics_before.txt / metrics_after.txt   raw Prometheus expositions
    deltas.json                              per-counter deltas + verdict
    trace.jsonl                              Chrome/Perfetto request spans
    requests.jsonl                           structured JSON request logs
    fleet-trace.json / fleet_verdict.json / flight/   (--fleet leg)
    alerts.json / history.json / explain.json / trajectory.jsonl (--fleet)

Usage:  JAX_PLATFORMS=cpu python scripts/obs_drill.py [--out-dir obs-drill]
                                                      [--fleet]
Exit 0 only if every assertion of the selected leg holds.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# counter -> the fault class whose visibility it proves
WATCHED = {
    "dllama_admission_rejections_total": "queue overflow (429)",
    "dllama_scheduler_crashes_total": "scheduler crash (503)",
    "dllama_numeric_quarantines_total": "poisoned logits (quarantine)",
    "dllama_deadline_expirations_total": "deadline expiry (504)",
    "dllama_http_requests_total": "request accounting",
}


def parse_exposition(text: str) -> dict:
    """Family name -> summed value across its series (labels collapsed:
    the drill asserts movement, not attribution)."""
    totals: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        sample, _, value = line.rpartition(" ")
        name = sample.partition("{")[0]
        # fold histogram series into their family's count
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
                break
        try:
            totals[name] = totals.get(name, 0.0) + float(value)
        except ValueError:
            pass
    return totals


def request(port, method, path, body=None, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def chat(**kw):
    body = {"model": "drill", "max_tokens": 8, "temperature": 0.0,
            "messages": [{"role": "user", "content": "observability drill"}]}
    body.update(kw)
    return body


def series_sum(text: str, family: str, must_contain: str = "") -> float:
    """Sum one family's sample values across all its series, optionally
    restricted to series whose label block contains ``must_contain``."""
    total = 0.0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        sample, _, value = line.rpartition(" ")
        if sample.partition("{")[0] != family or must_contain not in sample:
            continue
        try:
            total += float(value)
        except ValueError:
            pass
    return total


def fleet_main(args) -> int:
    """The --fleet leg: real router + 2 replica subprocesses, then assert
    stitching, federation arithmetic, and the SIGTERM flight dumps."""
    import glob
    import signal
    import socket
    import subprocess
    import time

    import numpy as np

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.quants import blocks

    out = os.path.abspath(args.out_dir)
    art = os.path.join(out, "artifacts")
    os.makedirs(art, exist_ok=True)
    model, tokp = os.path.join(art, "m.m"), os.path.join(art, "t.t")
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=300, seq_len=96,
                     weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    write_model(model, spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(
                    np.float32) for e in tensor_plan(spec)})
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
             + [b"hi"] * 41)
    write_tokenizer(tokp, TokenizerData(
        vocab=vocab, scores=[0.0] * 300, bos_id=1, eos_id=2))

    trace = os.path.join(out, "fleet-trace.json")
    flight_dir = os.path.join(out, "flight")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               DLLAMA_TRACE=trace, DLLAMA_FLIGHT=flight_dir)
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU children must not register
    #   the axon TPU plugin (single-session tunnel blocks a 2nd registrant)

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    router_port, base_port = free_port(), free_port() + 1000
    fleet_log = open(os.path.join(out, "fleet.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu.cli", "fleet",
         "--model", model, "--tokenizer", tokp,
         "--replicas", "2", "--base-port", str(base_port),
         "--host", "127.0.0.1", "--port", str(router_port),
         "--probe-interval", "0.3", "--ready-timeout", "240",
         # dense history sampling + a 1-microsecond interactive TTFT
         # target: every real chat is an SLO breach, so the burn-rate
         # engine must fire — and, with the burn windows shrunk to
         # drill scale, resolve again once the drill goes idle
         "--ts-interval", "0.25",
         "--slo-classes", "interactive:ttft=0.001",
         "--log-dir", os.path.join(out, "logs"),
         "--replica-arg", "--batch-window 5 --batch-max 2 --tp 1 "
                          "--burn-short 3 --burn-long 6"],
        env=env, cwd=REPO, stdout=fleet_log, stderr=subprocess.STDOUT)

    failures = []
    drilled_ids = []
    try:
        deadline = time.monotonic() + 300
        up = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet exited early ({proc.returncode}); see fleet.log")
            try:
                status, _ = request(router_port, "GET", "/ready", timeout=2)
                if status == 200:
                    up = True
                    break
            except OSError:
                pass  # front door not listening yet — keep polling
            time.sleep(0.5)
        if not up:
            raise RuntimeError("fleet front door never became ready")
        print(f"fleet up: router :{router_port} -> replicas "
              f":{base_port},:{base_port + 1}")

        for i in range(3):
            conn = http.client.HTTPConnection("127.0.0.1", router_port,
                                              timeout=120)
            conn.request("POST", "/v1/chat/completions",
                         body=json.dumps(chat(model="m", max_tokens=4)),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            rid = resp.getheader("X-Request-Id")
            timing = resp.getheader("Server-Timing") or ""
            conn.close()
            if resp.status != 200:
                failures.append(f"chat #{i} returned {resp.status}")
            if rid:
                drilled_ids.append(rid)
            if i == 0 and "total;dur=" not in timing:
                failures.append(
                    f"router response lacks Server-Timing: {timing!r}")
        print(f"drilled {len(drilled_ids)} chat request(s) through the "
              f"front door")

        # -- SLO burn-rate cycle: the microsecond TTFT target makes the
        #    drilled chats a breach in both burn windows -> federated
        #    /alerts must show interactive:ttft FIRING; then, idle, the
        #    burst ages out of the windows and it must RESOLVE (the
        #    hysteresis needs resolve_after consecutive healthy evals)
        alert_snaps = {}

        def poll_alerts(phase, want_firing, deadline_s):
            deadline = time.monotonic() + deadline_s
            payload = None
            while time.monotonic() < deadline:
                status, data = request(router_port, "GET", "/alerts",
                                       timeout=10)
                if status == 200:
                    payload = json.loads(data)
                    alert_snaps[phase] = payload
                    if bool(payload.get("firing", 0)) == want_firing:
                        return payload
                time.sleep(0.3)
            return None

        fired = poll_alerts("fired", True, 30)
        if fired is None:
            failures.append(
                "/alerts never fired after the SLO breach burst "
                f"(last: {alert_snaps.get('fired')})")
        else:
            slos = sorted({a["slo"]
                           for r in fired.get("replicas", {}).values()
                           for a in r.get("alerts", [])
                           if a.get("state") == "firing"})
            print(f"  alerts FIRING: {slos}")
            if "interactive:ttft" not in slos:
                failures.append(
                    f"firing alerts {slos} lack interactive:ttft")

        # the transition must be in the flight ring while firing (the
        # post-drain dump assertion below only sees the ring's tail)
        status, data = request(router_port, "GET", "/debug/flight",
                               timeout=30)
        if status == 200:
            report = json.loads(data)
            kinds = {ev.get("kind")
                     for snap in report.get("replicas", {}).values()
                     for ev in snap.get("events", [])}
            if fired is not None and "alert" not in kinds:
                failures.append(
                    f"no 'alert' transition in any replica flight ring "
                    f"while /alerts was firing (kinds: {sorted(kinds)})")

        # -- federated time-series history: non-empty window for the
        #    router's own registry and for every replica
        status, data = request(router_port, "GET",
                               "/metrics/history?window=120", timeout=30)
        if status != 200:
            failures.append(f"/metrics/history returned {status}")
        else:
            hist = json.loads(data)
            with open(os.path.join(out, "history.json"), "w") as f:
                json.dump(hist, f, indent=2, sort_keys=True)
            if not (hist.get("router") or {}).get("series"):
                failures.append(
                    "router /metrics/history window has no series")
            reps = hist.get("replicas") or {}
            if len(reps) != 2:
                failures.append(
                    f"/metrics/history federated {sorted(reps)}, "
                    "wanted 2 replicas")
            for rname, pay in reps.items():
                if not (pay.get("series") or {}):
                    failures.append(
                        f"replica {rname} history window is empty")
            # prefix affinity may pin every drilled chat to one replica,
            # so the served lane's series need only exist SOMEWHERE
            if reps and not any(
                    k.startswith("dllama_class_ttft_ms")
                    for pay in reps.values()
                    for k in (pay.get("series") or {})):
                failures.append(
                    "no replica history holds the sampled per-class "
                    "TTFT percentile series")
            n_series = sum(len(p.get("series") or {})
                           for p in reps.values())
            print(f"  history window: {n_series} replica series "
                  f"+ {len((hist.get('router') or {}).get('series') or {})}"
                  " router series")

        resolved = poll_alerts("resolved", False, 45)
        if resolved is None:
            failures.append(
                "/alerts never resolved after the breach burst aged out "
                f"(last: {alert_snaps.get('resolved')})")
        else:
            print("  alerts RESOLVED (burst aged out of both windows)")
        with open(os.path.join(out, "alerts.json"), "w") as f:
            json.dump(alert_snaps, f, indent=2, sort_keys=True)

        # -- federation arithmetic: /metrics/fleet sums == per-replica sums
        status, data = request(router_port, "GET", "/metrics/fleet",
                               timeout=30)
        fed = data.decode()
        with open(os.path.join(out, "metrics_fleet.txt"), "w") as f:
            f.write(fed)
        if status != 200:
            failures.append(f"/metrics/fleet returned {status}")
        rep_texts = []
        for p in (base_port, base_port + 1):
            status, data = request(p, "GET", "/metrics", timeout=30)
            if status != 200:
                failures.append(f"replica :{p} /metrics returned {status}")
            rep_texts.append(data.decode())
            with open(os.path.join(out, f"metrics_replica_{p}.txt"),
                      "w") as f:
                f.write(rep_texts[-1])
        # chat-route counters are quiescent between the two scrapes (probe
        # traffic only touches /ready and /metrics series), so the sums
        # must agree EXACTLY
        for family, restrict in (
                ("dllama_http_requests_total", 'route="/v1/chat/completions"'),
                ("dllama_completion_tokens_total", "")):
            want = sum(series_sum(t, family, restrict) for t in rep_texts)
            got = series_sum(fed, family, restrict)
            label = f"{family}{{{restrict}}}" if restrict else family
            print(f"  federation {label}: fleet={got:g} replicas={want:g}")
            if got != want or want <= 0:
                failures.append(
                    f"federation mismatch for {label}: "
                    f"fleet={got:g} != sum(replicas)={want:g}")
        if 'replica="127.0.0.1:' not in fed:
            failures.append("/metrics/fleet series lack the replica label")

        # -- flight visibility while alive: router aggregates /debug/flight
        status, data = request(router_port, "GET", "/debug/flight",
                               timeout=30)
        if status != 200:
            failures.append(f"/debug/flight returned {status}")
        else:
            report = json.loads(data)
            if len(report.get("replicas", {})) != 2:
                failures.append(
                    f"/debug/flight aggregated {report.get('replicas')!r}, "
                    f"wanted 2 replicas")
    except Exception as e:
        failures.append(f"fleet drill aborted: {e!r}")
    finally:
        # SIGTERM: replicas dump their flight rings, drain, and the
        # supervisor stitches the trace parts into fleet-trace.json
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                rc = proc.wait(timeout=120)
                if rc != 0:
                    failures.append(f"fleet drain exited {rc}")
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                failures.append("fleet did not drain within 120s")
        fleet_log.close()

    # -- stitched merged trace: router + replica spans of one request on
    #    one timeline, tied by a flow arrow, on distinct process tracks
    n_stitched = 0
    try:
        raw = open(trace).read()
        if not raw.startswith("[\n"):
            failures.append("fleet-trace.json is not a Perfetto JSON array")
        events = [json.loads(l.rstrip(","))
                  for l in raw.splitlines()[1:] if l.strip()]
        proxy = {e["args"].get("request_id"): e for e in events
                 if e.get("name") == "router_proxy" and "args" in e}
        reqs = {e["args"].get("request_id"): e for e in events
                if e.get("name") == "request" and "args" in e}
        flow_s = {e.get("id") for e in events if e.get("ph") == "s"}
        flow_f = {e.get("id") for e in events if e.get("ph") == "f"}
        for rid in drilled_ids:
            if (rid in proxy and rid in reqs
                    and proxy[rid].get("pid") != reqs[rid].get("pid")
                    and reqs[rid]["args"].get("parent_span") in
                    (flow_s & flow_f)):
                n_stitched += 1
        if n_stitched < 1:
            failures.append(
                f"no stitched request in merged trace "
                f"(proxy spans for {sorted(proxy)}, replica spans for "
                f"{sorted(reqs)}, flows s={sorted(flow_s)} "
                f"f={sorted(flow_f)})")
        names = {e["args"].get("name") for e in events
                 if e.get("name") == "process_name"}
        if "router" not in names or not any(
                str(n).startswith("replica:") for n in names):
            failures.append(f"merged trace process tracks wrong: {names}")
    except OSError as e:
        failures.append(f"merged trace unreadable: {e!r}")

    # -- SIGTERM flight dumps: one black box per replica, holding the
    #    drilled request ids in its recent events
    dumps = sorted(glob.glob(os.path.join(flight_dir, "flight-*.json")))
    if len(dumps) < 2:
        failures.append(
            f"expected >=2 flight dumps under {flight_dir}, got {dumps}")
    seen_ids = set()
    for path in dumps:
        try:
            d = json.load(open(path))
        except (OSError, ValueError) as e:
            failures.append(f"flight dump {path} unreadable: {e!r}")
            continue
        seen_ids.update(ev.get("request_id") for ev in d.get("events", []))
    if drilled_ids and not (seen_ids & set(drilled_ids)):
        failures.append(
            f"no drilled request id in any flight dump "
            f"(drilled {drilled_ids}, dumps held {sorted(seen_ids)})")

    # -- cli explain: the forensics join over the merged trace + flight
    #    dumps must produce a waterfall whose replica phase sum is within
    #    tolerance of the router-measured wall time (generous bounds:
    #    CI boxes jitter, but a sum at 10% or 300% of wall means the
    #    join picked up the wrong spans)
    explain_ok = False
    explain_docs = []
    for rid in drilled_ids:
        exp = subprocess.run(
            [sys.executable, "-m", "dllama_tpu.cli", "explain", rid,
             "--trace", trace, "--flight", flight_dir, "--json"],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=60)
        try:
            wf = json.loads(exp.stdout)
        except ValueError:
            failures.append(
                f"cli explain {rid} emitted no JSON "
                f"(rc={exp.returncode}, stderr={exp.stderr[-200:]!r})")
            continue
        explain_docs.append(wf)
        if not wf.get("rows") or not wf.get("wall_ms"):
            continue
        cov = wf["phase_sum_ms"] / wf["wall_ms"]
        print(f"  explain {rid}: wall {wf['wall_ms']:.1f}ms, phase sum "
              f"{wf['phase_sum_ms']:.1f}ms ({cov:.0%} coverage, "
              f"{len(wf['rows'])} spans, {len(wf['events'])} marks)")
        if 0.25 <= cov <= 1.75:
            explain_ok = True
    if drilled_ids and not explain_ok:
        failures.append(
            "no drilled request produced an explain waterfall whose "
            "phase sum is within tolerance of wall time")
    with open(os.path.join(out, "explain.json"), "w") as f:
        json.dump(explain_docs, f, indent=2, sort_keys=True)

    verdict = {"ok": not failures, "failures": failures,
               "stitched_requests": n_stitched,
               "drilled_request_ids": drilled_ids,
               "flight_dumps": [os.path.basename(p) for p in dumps]}
    with open(os.path.join(out, "fleet_verdict.json"), "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)

    # the drill leaves its own trajectory row (same durable format the
    # bench writes), so CI uploads a non-empty trajectory artifact even
    # on pure-CPU runners
    from dllama_tpu.obsv import trajectory
    trajectory.append_row(
        "obs_drill_fleet", "ok" if not failures else "error",
        result={"metric": "obs_drill_fleet",
                "stitched_requests": n_stitched,
                "flight_dumps": len(dumps)},
        error="; ".join(failures) or None,
        path=os.path.join(out, "trajectory.jsonl"))

    print(f"\nstitched requests in merged trace: {n_stitched}")
    print(f"flight dumps: {len(dumps)} -> {flight_dir}")
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        return 1
    print("fleet observability drill: stitched trace + exact federation + "
          "flight dumps + SLO alert cycle + history + explain all verified")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-dir", default="obs-drill")
    ap.add_argument("--fleet", action="store_true",
                    help="run the fleet leg (subprocess router + replicas) "
                         "instead of the single-process fault drill")
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)
    if args.fleet:
        return fleet_main(args)

    from dllama_tpu import faults, observability
    from dllama_tpu.models import llama
    from dllama_tpu.runtime.generate import Engine
    from dllama_tpu.runtime.sampler import SamplerConfig
    from dllama_tpu.serving.api_server import ServerState, create_server
    from tests.test_api_server import make_tokenizer
    from tests.test_llama_forward import tiny_cfg

    observability.configure_trace(os.path.join(args.out_dir, "trace.jsonl"))
    log_stream = open(os.path.join(args.out_dir, "requests.jsonl"), "w")

    tok = make_tokenizer()
    cfg = tiny_cfg(vocab_size=tok.vocab_size, seq_len=512, dim=32, kv_dim=16,
                   head_size=8, hidden_dim=64)
    params = llama.random_params(cfg, seed=13)
    engine = Engine(cfg, params, SamplerConfig(temperature=0.0, seed=1))
    state = ServerState(engine, tok, cfg, model_name="drill",
                        template="llama3", batch_window_ms=5.0, batch_max=4,
                        queue_depth=4, log_json=True, log_stream=log_stream)
    srv = create_server(state, host="127.0.0.1", port=0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def scrape(fname: str) -> dict:
        status, data = request(port, "GET", "/metrics", timeout=30)
        assert status == 200, f"/metrics returned {status}"
        text = data.decode()
        with open(os.path.join(args.out_dir, fname), "w") as f:
            f.write(text)
        return parse_exposition(text)

    def expect(label: str, want: int, got: int) -> None:
        ok = "ok" if got == want else f"UNEXPECTED (wanted {want})"
        print(f"  {label}: HTTP {got} [{ok}]")

    try:
        # warm-up: one healthy request so latency series exist
        status, _ = request(port, "POST", "/v1/chat/completions", chat())
        expect("healthy request", 200, status)
        before = scrape("metrics_before.txt")

        print("firing fault classes:")
        # queue overflow -> 429
        tickets = [state.gate.acquire() for _ in range(4)]
        try:
            status, _ = request(port, "POST", "/v1/chat/completions", chat(),
                                timeout=30)
            expect("queue overflow", 429, status)
        finally:
            for t in tickets:
                state.gate.release(t)

        # scheduler crash -> 503 (supervisor restarts it)
        faults.install("scheduler:raise:times=1")
        status, _ = request(port, "POST", "/v1/chat/completions", chat())
        faults.clear()
        expect("scheduler crash", 503, status)

        # poisoned logits -> numeric quarantine -> 500
        faults.install("logits:nan:after=2")
        status, _ = request(port, "POST", "/v1/chat/completions", chat())
        faults.clear()
        expect("poisoned logits", 500, status)

        # deadline expiry -> 504
        state.request_timeout = 0.0001
        status, _ = request(port, "POST", "/v1/chat/completions",
                            chat(max_tokens=32))
        state.request_timeout = 0.0
        expect("deadline expiry", 504, status)

        # prove the server still serves after the whole gauntlet
        status, _ = request(port, "POST", "/v1/chat/completions", chat())
        expect("post-gauntlet request", 200, status)

        after = scrape("metrics_after.txt")
    finally:
        srv.shutdown()
        observability.configure_trace(None)
        log_stream.close()

    deltas = {name: after.get(name, 0.0) - before.get(name, 0.0)
              for name in WATCHED}
    failures = [f"{name} ({why}) did not move"
                for name, why in WATCHED.items() if deltas[name] <= 0]

    trace_file = os.path.join(args.out_dir, "trace.jsonl")
    raw = open(trace_file).read()
    events = [json.loads(l.rstrip(","))
              for l in raw.splitlines()[1:] if l.strip()]
    n_requests = sum(1 for e in events if e.get("name") == "request")
    if not raw.startswith("[\n") or n_requests < 5:
        failures.append(
            f"trace.jsonl malformed or sparse ({n_requests} request spans)")

    verdict = {"ok": not failures, "deltas": deltas, "failures": failures,
               "trace_request_spans": n_requests}
    with open(os.path.join(args.out_dir, "deltas.json"), "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)

    print("\ncounter deltas:")
    for name, d in sorted(deltas.items()):
        print(f"  {name}: +{d:g}")
    print(f"trace spans: {n_requests} requests -> {trace_file}")
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        return 1
    print("observability drill: every fault class moved a counter")
    return 0


if __name__ == "__main__":
    sys.exit(main())
