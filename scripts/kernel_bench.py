"""Microbench the fused dequant-matmul kernels at decode shapes.

The axon tunnel makes naive timing lie twice: dispatch is async (so
``block_until_ready`` on a device buffer can return before execution), and a
real host sync (pulling bytes) costs a fixed ~70 ms round trip. So this
bench times ``iters`` and ``2*iters`` chained kernel calls inside one jitted
``lax.scan`` each, with a host pull at the end, and reports the DIFFERENCE —
the fixed round trip and compile-cached dispatch cancel, leaving pure
device time per call. Effective GB/s is against the bytes the kernel must
stream (weights + scales; activations are noise at T=1).

Usage: python scripts/kernel_bench.py [q40|q80|bf16|all] [K] [O] [iters]

``gather`` mode microbenches the TP activation wire instead of the matmul
kernels: the plain fused all-gather vs the Q80-compressed payload vs the
``lax.ppermute`` ring schedule (collectives.RingAxis — what ``--tp-overlap``
pipelines against the other microbatch's compute), at decode activation
sizes (T rows x F features, gathered across all visible devices). Same
difference-timing idiom, so the tunnel round trip cancels.

Usage: python scripts/kernel_bench.py gather [F] [T] [iters]

``fused`` mode times the two decode epilogue fusions against their unfused
compositions at decode activation sizes — rmsnorm folded into the q40/q80
projection (DLLAMA_FUSE_NORM's kernel) vs rmsnorm-then-qmatmul, and the
one-pass rope+cache write (DLLAMA_FUSE_ROPE_CACHE's kernel) vs
apply_rope + dynamic_update_slice. Same difference-timing idiom; each pair
appends a delta row (fused_ms, unfused_ms, delta_ms) to
results/trajectory.jsonl so the win is tracked across rounds, not eyeballed.

Usage: python scripts/kernel_bench.py fused [K] [O] [iters] [T]

``reduce`` mode microbenches the row-parallel reduce direction
(``--tp-reduce``) at decode partial-sum shapes: a fused ``jax.lax.psum``
vs the pinned-order ``lax.ppermute`` ring reduce-scatter(+gather) vs the
Q80-compressed ring (int8 quants + bitcast f32 scales per hop). Each
schedule appends a row to results/trajectory.jsonl with its modeled
wire bytes, so the quantized ring's win (or loss) on real hardware is
tracked across rounds. Same difference-timing idiom.

Usage: python scripts/kernel_bench.py reduce [F] [T] [iters]
"""

import functools
import sys
import time

import jax

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from _platform import apply_platform_override  # noqa: E402

apply_platform_override(jax)
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(
    __import__("os").path.abspath(__file__))))

from dllama_tpu.ops import qmatmul  # noqa: E402


def _timed_host_sync(run, *args, reps=3):
    float(np.asarray(run(*args)))  # compile + warm
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(run(*args)))
        best = min(best, time.perf_counter() - t0)
    return best


def bench(kind, K, O, iters=256, T=1):
    rng = np.random.default_rng(0)
    if kind == "bf16":
        w = jnp.asarray(rng.standard_normal((K, O)).astype(np.float32)).astype(jnp.bfloat16)
        nbytes = w.nbytes
        mm = lambda x, w: x @ w
        wargs = (w,)
    else:
        qt = qmatmul.quantize_tensor(
            rng.standard_normal((K, O)).astype(np.float32), kind)
        nbytes = qt.w.nbytes + qt.s.nbytes + qt.s2.nbytes
        mm = lambda x, qt: qmatmul.qmatmul(x, qt)
        wargs = (qt,)

    @functools.partial(jax.jit, static_argnames=("n",))
    def run(x, *w, n):
        def step(x, _):
            y = mm(x, *w)
            y = y[:, :K] if O >= K else jnp.pad(y, ((0, 0), (0, K - O)))
            return (y * 1e-2).astype(x.dtype), ()
        x, _ = jax.lax.scan(step, x, None, length=n)
        return jnp.sum(x.astype(jnp.float32))

    x = jnp.asarray(rng.standard_normal((T, K)).astype(np.float32)).astype(jnp.bfloat16)
    t1 = _timed_host_sync(functools.partial(run, n=iters), x, *wargs)
    t2 = _timed_host_sync(functools.partial(run, n=2 * iters), x, *wargs)
    ms = max(t2 - t1, 1e-9) * 1e3 / iters
    gbs = nbytes / (ms * 1e-3) / 1e9
    print(f"{kind:5s} K={K} O={O} T={T}: {ms:7.3f} ms/call  "
          f"{nbytes/1e6:8.1f} MB streamed  -> {gbs:7.1f} GB/s effective"
          f"   [t({iters})={t1*1e3:.0f}ms t({2*iters})={t2*1e3:.0f}ms]",
          flush=True)
    return ms, gbs


def bench_gather(F=4096, T=1, iters=256):
    """Time one TP activation gather three ways at a decode shape: plain
    fused all-gather, Q80-compressed payload (1.125 bytes/feature in ONE
    collective), and the ppermute ring schedule the overlap mode uses.
    Wire bytes are the (tp-1)/tp fraction each chip must receive."""
    from dllama_tpu.parallel import collectives
    from dllama_tpu.parallel.mesh import tp_mesh

    from dllama_tpu import compat

    tp = len(jax.devices())
    if tp < 2:
        raise SystemExit(
            "gather mode needs >1 device (TPU slice, or CPU with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    mesh = tp_mesh(tp)
    f_local = F // tp // 32 * 32  # local shard, q80-block aligned
    F_eff = f_local * tp
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((T, F_eff)).astype(np.float32)
                    ).astype(jnp.bfloat16)

    results = {}
    for name, axis, compress in (
        ("plain", "tp", False),
        ("q80", "tp", True),
        ("ring", collectives.RingAxis("tp"), False),
        ("ring+q80", collectives.RingAxis("tp"), True),
    ):
        def tp_gather(xs, _axis=axis, _c=compress):
            g = collectives.gather_columns(xs, _axis, compress=_c)
            # feed the local shard back in so scan iterations chain (no CSE)
            idx = jax.lax.axis_index("tp")
            lo = idx * f_local
            return (jax.lax.dynamic_slice_in_dim(g, lo, f_local, axis=-1)
                    * jnp.bfloat16(1.0))

        sharded = compat.shard_map(
            tp_gather, mesh=mesh,
            in_specs=jax.sharding.PartitionSpec(None, "tp"),
            out_specs=jax.sharding.PartitionSpec(None, "tp"))

        @functools.partial(jax.jit, static_argnames=("n",))
        def run(xs, n):
            def step(xs, _):
                return sharded(xs), ()
            xs, _ = jax.lax.scan(step, xs, None, length=n)
            return jnp.sum(xs.astype(jnp.float32))

        t1 = _timed_host_sync(functools.partial(run, n=iters), x)
        t2 = _timed_host_sync(functools.partial(run, n=2 * iters), x)
        ms = max(t2 - t1, 1e-9) * 1e3 / iters
        wire = (T * F_eff * (1.125 if compress else 2.0)) * (tp - 1) / tp
        results[name] = ms
        print(f"gather {name:8s} F={F_eff} T={T} tp={tp}: {ms:7.4f} ms/call"
              f"  {wire/1e3:7.1f} KB wire/chip"
              f"   [t({iters})={t1*1e3:.0f}ms t({2*iters})={t2*1e3:.0f}ms]",
              flush=True)
    return results


def bench_reduce(F=4096, T=1, iters=256):
    """Time one full-width f32 partial-sum reduction three ways at a
    decode shape: the fused ``jax.lax.psum`` (XLA's schedule, baseline),
    the pinned-order ring reduce-scatter + gather (``--tp-reduce plain``
    — bit-reproducible), and the Q80-compressed ring (``--tp-reduce
    q80``).  Ring wire bytes per chip: (tp-1) hops x F/tp chunk at 4.0
    (plain) or 1.125 (q80) bytes/feature for the scatter half, plus the
    (tp-1)/tp x F x 4.0 trailing f32 gather."""
    from dllama_tpu import compat
    from dllama_tpu.obsv import trajectory
    from dllama_tpu.parallel import collectives
    from dllama_tpu.parallel.mesh import tp_mesh

    tp = len(jax.devices())
    if tp < 2:
        raise SystemExit(
            "reduce mode needs >1 device (TPU slice, or CPU with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    mesh = tp_mesh(tp)
    F_eff = F // (32 * tp) * (32 * tp)  # whole q80-aligned chunks per device
    rng = np.random.default_rng(0)
    # [tp, T, F]: axis 0 sharded, so each device carries one full-width partial
    x = jnp.asarray(rng.standard_normal((tp, T, F_eff)).astype(np.float32))

    results = {}
    for name, red in (
        ("psum", lambda p: jax.lax.psum(p, "tp")),
        ("ring", lambda p: collectives.reduce_columns(p, "tp", False)),
        ("ring+q80", lambda p: collectives.reduce_columns(p, "tp", True)),
    ):
        def tp_reduce(xs, _red=red):
            # scale down so the chained sum of sums stays finite over the scan
            return (_red(xs[0]) * np.float32(1.0 / (2.0 * tp)))[None]

        sharded = compat.shard_map(
            tp_reduce, mesh=mesh,
            in_specs=jax.sharding.PartitionSpec("tp"),
            out_specs=jax.sharding.PartitionSpec("tp"))

        @functools.partial(jax.jit, static_argnames=("n",))
        def run(xs, n):
            def step(xs, _):
                return sharded(xs), ()
            xs, _ = jax.lax.scan(step, xs, None, length=n)
            return jnp.sum(xs)

        t1 = _timed_host_sync(functools.partial(run, n=iters), x)
        t2 = _timed_host_sync(functools.partial(run, n=2 * iters), x)
        ms = max(t2 - t1, 1e-9) * 1e3 / iters
        scat_feat = 1.125 if name == "ring+q80" else 4.0
        if name == "psum":
            wire = T * F_eff * 4.0 * 2 * (tp - 1) / tp  # reduce-scatter+gather
        else:
            wire = T * F_eff * (tp - 1) / tp * (scat_feat + 4.0)
        results[name] = ms
        print(f"reduce {name:8s} F={F_eff} T={T} tp={tp}: {ms:7.4f} ms/call"
              f"  {wire/1e3:7.1f} KB wire/chip"
              f"   [t({iters})={t1*1e3:.0f}ms t({2*iters})={t2*1e3:.0f}ms]",
              flush=True)
        trajectory.append_row(
            f"kernel_reduce/{name}", "ok",
            result={"metric": f"{name}_ms", "value": ms,
                    "wire_kb_chip": wire / 1e3, "F": F_eff, "T": T, "tp": tp,
                    "backend": jax.default_backend()})
    return results


def _timed_scan(step_fn, carry, iters):
    """Difference-timed ms/call for ``step_fn`` chained through one jitted
    scan — same tunnel-cancelling idiom as bench()."""
    @functools.partial(jax.jit, static_argnames=("n",))
    def run(c, n):
        c, _ = jax.lax.scan(lambda c, _: (step_fn(c), ()), c, None, length=n)
        return jnp.sum(jax.tree.leaves(c)[0].astype(jnp.float32))

    t1 = _timed_host_sync(functools.partial(run, n=iters), carry)
    t2 = _timed_host_sync(functools.partial(run, n=2 * iters), carry)
    return max(t2 - t1, 1e-9) * 1e3 / iters


def bench_fused(kind="q40", K=4096, O=4096, iters=256, T=1):
    """Fused-vs-unfused delta for both decode epilogues; one trajectory
    row per pair. delta_ms = fused - unfused, so negative is a win and
    the trajectory comparator's "_ms means lower-is-better" rule flags a
    fusion that stops paying for itself."""
    from dllama_tpu.obsv import trajectory
    from dllama_tpu.ops import fused_rope_cache, rope
    from dllama_tpu.ops.norms import rmsnorm

    rng = np.random.default_rng(0)
    rows = {}

    # -- rmsnorm folded into the quantized projection -----------------------
    qt = qmatmul.quantize_tensor(
        rng.standard_normal((K, O)).astype(np.float32) * 0.1, kind)
    nw = jnp.asarray(rng.standard_normal((K,)).astype(np.float32) * 0.5 + 1.0)
    x = jnp.asarray(rng.standard_normal((T, K)).astype(np.float32)
                    ).astype(jnp.bfloat16)

    def _chain(y):  # feed output back as the next activation (no CSE)
        y = y[:, :K] if O >= K else jnp.pad(y, ((0, 0), (0, K - O)))
        return (y * 1e-2).astype(jnp.bfloat16)

    norm_ms = {
        "unfused": _timed_scan(
            lambda c: _chain(qmatmul.qmatmul(rmsnorm(c, nw, 1e-5), qt)),
            x, iters),
        "fused": _timed_scan(
            lambda c: _chain(qmatmul.qmatmul_norm(c, nw, qt)), x, iters),
    }
    rows[f"norm_{kind}"] = norm_ms

    # -- rope + cache write -------------------------------------------------
    L, S, n_kv, hd = 1, 2048, 8, 128
    k0 = jnp.asarray(rng.standard_normal((T, n_kv, hd)).astype(np.float32)
                     ).astype(jnp.bfloat16)
    kc0 = jnp.zeros((L, S, n_kv, hd), jnp.bfloat16)
    cos_t, sin_t = map(jnp.asarray, rope.rope_table(S, hd, 10000.0))
    pos, layer = jnp.int32(S // 2), jnp.int32(0)
    cos = jax.lax.dynamic_slice_in_dim(cos_t, pos, T)[:, None, :]
    sin = jax.lax.dynamic_slice_in_dim(sin_t, pos, T)[:, None, :]

    def rope_unfused(c):
        kc, vc = c
        kr = rope.apply_rope(k0, cos, sin, rope.INTERLEAVED)
        z = jnp.int32(0)
        kc = jax.lax.dynamic_update_slice(kc, kr.astype(kc.dtype)[None],
                                          (layer, pos, z, z))
        vc = jax.lax.dynamic_update_slice(vc, k0.astype(vc.dtype)[None],
                                          (layer, pos, z, z))
        return kc, vc

    def rope_fused(c):
        return fused_rope_cache.rope_cache_update(
            k0, k0, cos, sin, c[0], c[1], pos, layer, rope.INTERLEAVED)

    rope_ms = {
        "unfused": _timed_scan(rope_unfused, (kc0, kc0), iters),
        "fused": _timed_scan(rope_fused, (kc0, kc0), iters),
    }
    rows["rope_cache"] = rope_ms

    for name, ms in rows.items():
        delta = ms["fused"] - ms["unfused"]
        print(f"fused {name:10s} K={K} O={O} T={T}: "
              f"fused {ms['fused']:7.4f} ms  unfused {ms['unfused']:7.4f} ms"
              f"  delta {delta:+.4f} ms/call", flush=True)
        trajectory.append_row(
            f"kernel_fused/{name}", "ok",
            result={"metric": f"{name}_delta_ms", "value": delta,
                    "fused_ms": ms["fused"], "unfused_ms": ms["unfused"],
                    "K": K, "O": O, "T": T,
                    "backend": jax.default_backend()})
    return rows


if __name__ == "__main__":
    kind = sys.argv[1] if len(sys.argv) > 1 else "all"
    if kind == "gather":
        F = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
        T = int(sys.argv[3]) if len(sys.argv) > 3 else 1
        iters = int(sys.argv[4]) if len(sys.argv) > 4 else 256
        bench_gather(F, T, iters)
        sys.exit(0)
    if kind == "reduce":
        F = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
        T = int(sys.argv[3]) if len(sys.argv) > 3 else 1
        iters = int(sys.argv[4]) if len(sys.argv) > 4 else 256
        bench_reduce(F, T, iters)
        sys.exit(0)
    if kind == "fused":
        K = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
        O = int(sys.argv[3]) if len(sys.argv) > 3 else 4096
        iters = int(sys.argv[4]) if len(sys.argv) > 4 else 64
        T = int(sys.argv[5]) if len(sys.argv) > 5 else 1
        for k in ("q40", "q80"):
            bench_fused(k, K, O, iters, T)
        sys.exit(0)
    K = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    O = int(sys.argv[3]) if len(sys.argv) > 3 else 11008
    iters = int(sys.argv[4]) if len(sys.argv) > 4 else 256
    kinds = ("q40", "q80", "bf16") if kind == "all" else (kind,)
    for k in kinds:
        bench(k, K, O, iters)
