"""Microbench the fused dequant-matmul kernels at decode shapes.

The axon tunnel makes naive timing lie twice: dispatch is async (so
``block_until_ready`` on a device buffer can return before execution), and a
real host sync (pulling bytes) costs a fixed ~70 ms round trip. So this
bench times ``iters`` and ``2*iters`` chained kernel calls inside one jitted
``lax.scan`` each, with a host pull at the end, and reports the DIFFERENCE —
the fixed round trip and compile-cached dispatch cancel, leaving pure
device time per call. Effective GB/s is against the bytes the kernel must
stream (weights + scales; activations are noise at T=1).

Usage: python scripts/kernel_bench.py [q40|q80|bf16|all] [K] [O] [iters]
"""

import functools
import sys
import time

import jax

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from _platform import apply_platform_override  # noqa: E402

apply_platform_override(jax)
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(
    __import__("os").path.abspath(__file__))))

from dllama_tpu.ops import qmatmul  # noqa: E402


def _timed_host_sync(run, *args, reps=3):
    float(np.asarray(run(*args)))  # compile + warm
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(run(*args)))
        best = min(best, time.perf_counter() - t0)
    return best


def bench(kind, K, O, iters=256, T=1):
    rng = np.random.default_rng(0)
    if kind == "bf16":
        w = jnp.asarray(rng.standard_normal((K, O)).astype(np.float32)).astype(jnp.bfloat16)
        nbytes = w.nbytes
        mm = lambda x, w: x @ w
        wargs = (w,)
    else:
        qt = qmatmul.quantize_tensor(
            rng.standard_normal((K, O)).astype(np.float32), kind)
        nbytes = qt.w.nbytes + qt.s.nbytes + qt.s2.nbytes
        mm = lambda x, qt: qmatmul.qmatmul(x, qt)
        wargs = (qt,)

    @functools.partial(jax.jit, static_argnames=("n",))
    def run(x, *w, n):
        def step(x, _):
            y = mm(x, *w)
            y = y[:, :K] if O >= K else jnp.pad(y, ((0, 0), (0, K - O)))
            return (y * 1e-2).astype(x.dtype), ()
        x, _ = jax.lax.scan(step, x, None, length=n)
        return jnp.sum(x.astype(jnp.float32))

    x = jnp.asarray(rng.standard_normal((T, K)).astype(np.float32)).astype(jnp.bfloat16)
    t1 = _timed_host_sync(functools.partial(run, n=iters), x, *wargs)
    t2 = _timed_host_sync(functools.partial(run, n=2 * iters), x, *wargs)
    ms = max(t2 - t1, 1e-9) * 1e3 / iters
    gbs = nbytes / (ms * 1e-3) / 1e9
    print(f"{kind:5s} K={K} O={O} T={T}: {ms:7.3f} ms/call  "
          f"{nbytes/1e6:8.1f} MB streamed  -> {gbs:7.1f} GB/s effective"
          f"   [t({iters})={t1*1e3:.0f}ms t({2*iters})={t2*1e3:.0f}ms]",
          flush=True)
    return ms, gbs


if __name__ == "__main__":
    kind = sys.argv[1] if len(sys.argv) > 1 else "all"
    K = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    O = int(sys.argv[3]) if len(sys.argv) > 3 else 11008
    iters = int(sys.argv[4]) if len(sys.argv) > 4 else 256
    kinds = ("q40", "q80", "bf16") if kind == "all" else (kind,)
    for k in kinds:
        bench(k, K, O, iters)
