"""Candidate q40 kernel optimizations, ready to A/B on the real chip.

The production kernel (ops.qmatmul) measured ~500 GB/s effective on 7B
shapes vs ~750 GB/s for a dense bf16 matvec (scripts/kernel_bench.py), i.e.
still VPU-dequant-bound, not HBM-bound. Variants here trade VPU ops for
bytes or MXU work; each is validated against dequantize() and timed with the
differencing harness. Integrate a variant only after it wins on hardware.

  A  production kernel (baseline)
  B  no-subtract: dequant w = q * s (drops the `- 8`), correcting with
     out -= 8 * (block_sums(x) @ s) — two tiny MXU dots OUTSIDE the kernel
     (re-reads the scale planes, +4% bytes, saves ~12% VPU)
  D  bf16 scale planes: same kernel, s/s2 stored bf16 — 20% -> 10% of bytes
     spent on scales (checkpoint deltas are f16, so bf16 rounds 3 mantissa
     bits: NOT bit-exact with the published file; opt-in if it wins)

  C  the PRODUCTION no-subtract path (what Q40_NOSUB=1 ships)
  E  int8-MXU accumulation: q80-quantized x, per-32-block int8xint8->int32
     MXU dots, scales applied to partials (the reference's Q40xQ80
     integer-dot idea, /root/reference/src/funcs.cpp:329-334, on the MXU)
  F  variant B with 2048-lane O tiles (tile_plan caps at 1024)
  G  variant B with bf16 scale copies for the correction dots only
  S  layer-stacked scalar-prefetch A/B (the decode scan's real form)

Usage: python scripts/qkernel_experiments.py [A|B|C|D|E|F|G|S|all] [K] [O]
"""

import functools
import os
import statistics
import sys
import time

import jax

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from _platform import apply_platform_override  # noqa: E402

apply_platform_override(jax)

import jax.numpy as jnp
import numpy as np

from dllama_tpu import compat

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(
    __import__("os").path.abspath(__file__))))

from dllama_tpu.ops import qmatmul  # noqa: E402
from dllama_tpu.ops.qmatmul import QK, QuantTensor  # noqa: E402


def variant_a(x, qt):
    # pin nosub=False: A is the subtracting-kernel baseline regardless of
    # the Q40_NOSUB production default
    return qmatmul.q40_matmul(x.astype(jnp.bfloat16), qt.w, qt.s, qt.s2,
                              nosub=False)


def _q40_nosub_kernel(*refs, acc_dtype):
    from jax.experimental import pallas as pl

    xlo_ref, xhi_ref, w_ref, slo_ref, shi_ref, o_ref = refs

    @pl.when(pl.program_id(2) == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    pk = w_ref[...].astype(jnp.int32)
    hk, bo = pk.shape
    lo = (pk & 0xF).astype(jnp.float32)        # 0..15, no -8
    hi = ((pk >> 4) & 0xF).astype(jnp.float32)
    nsb = slo_ref.shape[0]
    s_lo = jnp.reshape(
        jnp.broadcast_to(slo_ref[...][:, None, :], (nsb, QK, bo)), (hk, bo))
    s_hi = jnp.reshape(
        jnp.broadcast_to(shi_ref[...][:, None, :], (nsb, QK, bo)), (hk, bo))
    o_ref[...] += jnp.dot(xlo_ref[...], (lo * s_lo).astype(jnp.bfloat16),
                          preferred_element_type=acc_dtype)
    o_ref[...] += jnp.dot(xhi_ref[...], (hi * s_hi).astype(jnp.bfloat16),
                          preferred_element_type=acc_dtype)


@jax.jit
def variant_b(x, qt):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    packed, s_lo, s_hi = qt.w, qt.s, qt.s2
    O = packed.shape[1]
    K = packed.shape[0] * 2
    xp, t = qmatmul._pad_rows(qmatmul._pad_cols(x.astype(jnp.bfloat16), K))
    T = xp.shape[0]
    xr = xp.reshape(T, K // 64, 64)
    x_lo = xr[:, :, :QK].reshape(T, K // 2)
    x_hi = xr[:, :, QK:].reshape(T, K // 2)
    bk, bo = qmatmul.tile_plan("q40", K, O)
    bt = min(T, qmatmul.T_BLOCK)
    out = pl.pallas_call(
        functools.partial(_q40_nosub_kernel, acc_dtype=jnp.float32),
        grid=(pl.cdiv(T, bt), pl.cdiv(O, bo), K // bk),
        in_specs=[
            pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bk // 2, bo), lambda t_, o, k: (k, o)),
            pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
            pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
        ],
        out_specs=pl.BlockSpec((bt, bo), lambda t_, o, k: (t_, o)),
        out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() != "tpu",
    )(x_lo, x_hi, packed, s_lo, s_hi)
    # correction: sum_k (q-8)*s*x = sum q*s*x - 8 * sum_blocks s * blocksum(x)
    xs = xp.astype(jnp.float32).reshape(T, K // QK, QK).sum(-1)  # [T, K/32]
    xs_lo, xs_hi = xs[:, 0::2], xs[:, 1::2]  # even/odd 32-blocks -> planes
    corr = 8.0 * (xs_lo @ s_lo + xs_hi @ s_hi)
    return (out - corr)[:t]


def variant_c(x, qt):
    """The PRODUCTION no-subtract path (ops.qmatmul nosub=True): nosub
    Pallas kernel + the Pallas correction kernel (vs B's out-of-kernel jnp
    correction dots). This is what Q40_NOSUB=1 actually ships."""
    return qmatmul.q40_matmul(x.astype(jnp.bfloat16), qt.w, qt.s, qt.s2,
                              nosub=True)


def variant_d(x, qt):
    qd = QuantTensor(w=qt.w, s=qt.s.astype(jnp.bfloat16),
                     s2=qt.s2.astype(jnp.bfloat16), kind=qt.kind,
                     k_logical=qt.k_logical)
    return qmatmul.q40_matmul(x.astype(jnp.bfloat16), qd.w, qd.s, qd.s2,
                              nosub=False)


def _q40_int8_kernel(*refs):
    """Variant E compute: the reference's Q40xQ80 integer-dot idea
    (`/root/reference/src/funcs.cpp:329-334`, NEON vdotq_s32) mapped to the
    MXU's int8 path. x arrives pre-quantized q80-style (int8 + per-32-block
    f32 scale); each 32-row block runs an int8xint8->int32 MXU dot and the
    scale product (sx_b outer s_b) applies to the [bt, bo] PARTIAL — nsb x
    bo scale multiplies instead of the nosub kernel's hk x bo, trading the
    VPU dequant multiply for small-K MXU dots."""
    from jax.experimental import pallas as pl

    xlo_ref, xhi_ref, sxlo_ref, sxhi_ref, w_ref, slo_ref, shi_ref, o_ref = refs

    @pl.when(pl.program_id(2) == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    pk = w_ref[...].astype(jnp.int32)
    lo = (pk & 0xF).astype(jnp.int8)          # 0..15 fits int8; no -8
    hi = ((pk >> 4) & 0xF).astype(jnp.int8)
    nsb = slo_ref.shape[0]
    acc = jnp.zeros_like(o_ref[...])
    for i in range(nsb):
        xl = xlo_ref[:, i * QK:(i + 1) * QK]
        xh = xhi_ref[:, i * QK:(i + 1) * QK]
        dl = jax.lax.dot_general(
            xl, lo[i * QK:(i + 1) * QK, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32)
        dh = jax.lax.dot_general(
            xh, hi[i * QK:(i + 1) * QK, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32)
        acc += dl * (sxlo_ref[:, i:i + 1] * slo_ref[i, :][None, :])
        acc += dh * (sxhi_ref[:, i:i + 1] * shi_ref[i, :][None, :])
    o_ref[...] += acc


@jax.jit
def variant_e(x, qt):
    """int8-MXU accumulation (see _q40_int8_kernel). Adds x-quantization
    (q80-style, rel ~4e-3) on top of q40's own noise."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    packed, s_lo, s_hi = qt.w, qt.s, qt.s2
    O = packed.shape[1]
    K = packed.shape[0] * 2
    xp, t = qmatmul._pad_rows(qmatmul._pad_cols(x.astype(jnp.float32), K))
    T = xp.shape[0]
    # q80-quantize x per 32-block, split into the lo/hi planes matching the
    # packed layout (64-block: first 32 -> lo nibbles, last 32 -> hi)
    xb = xp.reshape(T, K // QK, QK)
    absmax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = absmax / 127.0
    xq = jnp.round(xb / jnp.where(scale == 0.0, 1.0, scale)).astype(jnp.int8)
    sx = scale[..., 0]  # [T, K/32]
    xr = xq.reshape(T, K // 64, 64)
    x_lo = xr[:, :, :QK].reshape(T, K // 2)
    x_hi = xr[:, :, QK:].reshape(T, K // 2)
    sx_lo, sx_hi = sx[:, 0::2], sx[:, 1::2]  # [T, K/64]

    bk, bo = qmatmul.tile_plan("q40", K, O)
    bt = min(T, qmatmul.T_BLOCK)
    out = pl.pallas_call(
        _q40_int8_kernel,
        grid=(pl.cdiv(T, bt), pl.cdiv(O, bo), K // bk),
        in_specs=[
            pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bt, bk // 64), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bt, bk // 64), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bk // 2, bo), lambda t_, o, k: (k, o)),
            pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
            pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
        ],
        out_specs=pl.BlockSpec((bt, bo), lambda t_, o, k: (t_, o)),
        out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() != "tpu",
    )(x_lo, x_hi, sx_lo, sx_hi, packed, s_lo, s_hi)
    # -8 correction against the SAME quantized x the kernel saw
    xs = (sx * xq.astype(jnp.float32).sum(-1))  # [T, K/32]
    xs_lo, xs_hi = xs[:, 0::2], xs[:, 1::2]
    corr = 8.0 * (xs_lo @ s_lo + xs_hi @ s_hi)
    return (out - corr)[:t]


@jax.jit
def variant_f(x, qt):
    """variant B with 2048-lane O tiles (tile_plan caps bo at 1024): fewer,
    fatter grid steps — tests whether the cap costs bandwidth at 7B widths
    (11008 -> six 2048-blocks with one masked boundary block)."""
    import functools as ft

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    packed, s_lo, s_hi = qt.w, qt.s, qt.s2
    O = packed.shape[1]
    K = packed.shape[0] * 2
    xp, t = qmatmul._pad_rows(qmatmul._pad_cols(x.astype(jnp.bfloat16), K))
    T = xp.shape[0]
    xr = xp.reshape(T, K // 64, 64)
    x_lo = xr[:, :, :QK].reshape(T, K // 2)
    x_hi = xr[:, :, QK:].reshape(T, K // 2)
    bk, _ = qmatmul.tile_plan("q40", K, O)
    bo = min(2048, qmatmul._pad_up(O, 128))
    bt = min(T, qmatmul.T_BLOCK)
    out = pl.pallas_call(
        functools.partial(_q40_nosub_kernel, acc_dtype=jnp.float32),
        grid=(pl.cdiv(T, bt), pl.cdiv(O, bo), K // bk),
        in_specs=[
            pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bk // 2, bo), lambda t_, o, k: (k, o)),
            pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
            pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
        ],
        out_specs=pl.BlockSpec((bt, bo), lambda t_, o, k: (t_, o)),
        out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() != "tpu",
    )(x_lo, x_hi, packed, s_lo, s_hi)
    xs = xp.astype(jnp.float32).reshape(T, K // QK, QK).sum(-1)
    xs_lo, xs_hi = xs[:, 0::2], xs[:, 1::2]
    corr = 8.0 * (xs_lo @ s_lo + xs_hi @ s_hi)
    return (out - corr)[:t]


#: variant G: B's kernel (f32 scales in-kernel) + CORRECTION dots reading
#: persistent bf16 scale copies — the nosub path's +100% scale re-read
#: becomes +50%, without D's in-kernel rounding (the correction term is
#: itself small, so bf16 rounding there is second-order). The bf16 copies
#: are cached per QuantTensor so the timed loop reads them from HBM, not
#: re-casts them.
_G_CACHE: dict = {}


def variant_g(x, qt):
    key = id(qt)
    # the cached entry keeps qt itself alive, so a recycled id() after GC
    # can never alias a different tensor's scales
    if key not in _G_CACHE or _G_CACHE[key][0] is not qt:
        _G_CACHE[key] = (qt, jnp.asarray(qt.s, jnp.bfloat16),
                         jnp.asarray(qt.s2, jnp.bfloat16))
    _, s_lo16, s_hi16 = _G_CACHE[key]
    return _variant_g_impl(x, qt, s_lo16, s_hi16)


@jax.jit
def _variant_g_impl(x, qt, s_lo_bf16, s_hi_bf16):
    import functools as ft

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    packed, s_lo, s_hi = qt.w, qt.s, qt.s2
    O = packed.shape[1]
    K = packed.shape[0] * 2
    xp, t = qmatmul._pad_rows(qmatmul._pad_cols(x.astype(jnp.bfloat16), K))
    T = xp.shape[0]
    xr = xp.reshape(T, K // 64, 64)
    x_lo = xr[:, :, :QK].reshape(T, K // 2)
    x_hi = xr[:, :, QK:].reshape(T, K // 2)
    bk, bo = qmatmul.tile_plan("q40", K, O)
    bt = min(T, qmatmul.T_BLOCK)
    out = pl.pallas_call(
        ft.partial(_q40_nosub_kernel, acc_dtype=jnp.float32),
        grid=(pl.cdiv(T, bt), pl.cdiv(O, bo), K // bk),
        in_specs=[
            pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
            pl.BlockSpec((bk // 2, bo), lambda t_, o, k: (k, o)),
            pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
            pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
        ],
        out_specs=pl.BlockSpec((bt, bo), lambda t_, o, k: (t_, o)),
        out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() != "tpu",
    )(x_lo, x_hi, packed, s_lo, s_hi)
    xs = xp.astype(jnp.float32).reshape(T, K // QK, QK).sum(-1)
    xs_lo, xs_hi = xs[:, 0::2], xs[:, 1::2]
    corr = 8.0 * (xs_lo @ s_lo_bf16.astype(jnp.float32)
                  + xs_hi @ s_hi_bf16.astype(jnp.float32))
    return (out - corr)[:t]


#: (fn, scale-plane byte multiplier): A reads scales once; B/C read them
#: twice (in-kernel dequant + the correction dots); D stores them bf16,
#: halving their bytes; E reads them twice plus x-quant scales (small);
#: F like B; G = f32 kernel read + bf16 correction read = 1.5x
VARIANTS = {"A": (variant_a, 1.0), "B": (variant_b, 2.0),
            "C": (variant_c, 2.0), "D": (variant_d, 0.5),
            "E": (variant_e, 2.0), "F": (variant_f, 2.0),
            "G": (variant_g, 1.5)}


def nbytes_of(qt, scale_mult):
    return qt.w.nbytes + (qt.s.nbytes + qt.s2.nbytes) * scale_mult


def check(name, fn, qt, K):
    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, K)).astype(np.float32)
    got = np.asarray(fn(jnp.asarray(x, jnp.bfloat16), qt), np.float32)
    want = x @ qmatmul.dequantize(qt)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    tol = 3e-2 if name != "D" else 4e-2  # D adds bf16 scale rounding
    print(f"{name}: rel-err {rel:.2e}", flush=True)
    return rel < tol


def timed(name, fn, qt, K, nbytes, n1=768, n2=1536, reps=5):
    @functools.partial(jax.jit, static_argnames=("n",))
    def run(x, n):
        def step(x, _):
            y = fn(x, qt)[:, :K]
            return (y * 1e-2).astype(x.dtype), ()
        x, _ = jax.lax.scan(step, x, None, length=n)
        return jnp.sum(x.astype(jnp.float32))

    x = jnp.asarray(np.random.default_rng(0).standard_normal((1, K)),
                    jnp.bfloat16)

    def go(n):
        float(np.asarray(run(x, n)))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(np.asarray(run(x, n)))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    ms = max(go(n2) - go(n1), 1e-9) * 1e3 / (n2 - n1)
    print(f"{name}: {ms:7.4f} ms/call -> {nbytes/(ms*1e-3)/1e9:7.1f} GB/s",
          flush=True)


def stacked_ab(K, O, L=8, n1=96, n2=192, reps=5):
    """A/B the LAYER-STACKED scalar-prefetch path (the decode scan's form):
    scan over L layers calling q40_matmul_stacked with nosub False vs True.
    This is the integration actually driving per-token decode latency —
    the flat-variant numbers above can't see prefetch/correction-kernel
    interactions."""
    rng = np.random.default_rng(0)
    qts = [qmatmul.quantize_tensor(
        rng.standard_normal((K, O)).astype(np.float32) * 0.1, "q40",
        to_device=False) for _ in range(L)]
    w = jnp.asarray(np.stack([q.w for q in qts]))
    s = jnp.asarray(np.stack([q.s for q in qts]))
    s2 = jnp.asarray(np.stack([q.s2 for q in qts]))
    nbytes = w.nbytes / L  # per layer-call; scales accounted via multiplier

    for name, nosub in (("S-sub", False), ("S-nosub", True)):
        # w/s/s2 are traced ARGUMENTS: closure capture would bake ~300 MB
        # of planes into the program as constants (the ablate_decode.py
        # tunnel-wedge bug all over again)
        @functools.partial(jax.jit, static_argnames=("n", "nosub"))
        def run(x, w, s, s2, n, nosub=nosub):
            def step(carry, i):
                y = qmatmul.q40_matmul_stacked(
                    carry, w, s, s2, i % jnp.int32(L), nosub=nosub)[:, :K]
                return (y * 1e-2).astype(carry.dtype), ()
            x, _ = jax.lax.scan(step, x, jnp.arange(n, dtype=jnp.int32))
            return jnp.sum(x.astype(jnp.float32))

        x = jnp.asarray(rng.standard_normal((1, K)), jnp.bfloat16)

        def go(n):
            float(np.asarray(run(x, w, s, s2, n)))
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                float(np.asarray(run(x, w, s, s2, n)))
                ts.append(time.perf_counter() - t0)
            return statistics.median(ts)

        ms = max(go(n2) - go(n1), 1e-9) * 1e3 / (n2 - n1)
        mult = 2.0 if nosub else 1.0
        nb = nbytes + (s.nbytes + s2.nbytes) / L * mult
        print(f"{name}: {ms:7.4f} ms/layer-call -> {nb/(ms*1e-3)/1e9:7.1f}"
              " GB/s", flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    K = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    O = int(sys.argv[3]) if len(sys.argv) > 3 else 11008
    on_tpu = jax.default_backend() == "tpu"
    if which in ("all", "S"):
        if on_tpu:
            stacked_ab(K, O)
        else:
            print("stacked A/B skipped: not on TPU", flush=True)
        if which == "S":
            sys.exit(0 if on_tpu else 1)
    qt = qmatmul.quantize_tensor(
        np.random.default_rng(0).standard_normal((K, O)).astype(np.float32) * 0.1,
        "q40")
    names = list(VARIANTS) if which == "all" else [which]
    for n in names:
        fn, scale = VARIANTS[n]
        if check(n, fn, qt, K) and on_tpu:
            timed(n, fn, qt, K, nbytes_of(qt, scale))
    if not on_tpu:
        print("(CPU interpret mode: correctness only, no timing)", flush=True)
