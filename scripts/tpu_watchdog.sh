#!/bin/bash
# Probe the axon TPU tunnel until it answers, then run the measurement
# battery. The relay serves one session at a time and can wedge for a while
# after a client dies — this keeps retrying instead of burning an operator's
# attention.
#
#   bash scripts/tpu_watchdog.sh [results_dir] [max_probes] [battery]
set -u
OUT=${1:-results}
MAX=${2:-120}
BATTERY=${3:-measure_all.sh}
# fail a typo'd battery name NOW, not after hours of probing
if [ ! -f "$(dirname "$0")/$BATTERY" ]; then
  echo "battery script not found: $(dirname "$0")/$BATTERY" >&2
  exit 1
fi
PROBE='
import time, jax, jax.numpy as jnp
t0 = time.time()
x = jnp.ones((256, 256), jnp.bfloat16)
jax.block_until_ready(x @ x)
print(f"TUNNEL_OK first_matmul={time.time()-t0:.1f}s")
'
for i in $(seq 1 "$MAX"); do
  echo "probe $i/$MAX $(date -u +%H:%M:%S)"
  if timeout -k 10 150 python -c "$PROBE" 2>&1 | grep TUNNEL_OK; then
    echo "tunnel is up — starting battery $BATTERY"
    exec bash "$(dirname "$0")/$BATTERY" "$OUT"
  fi
  sleep 120
done
echo "tunnel never came up after $MAX probes"
exit 1
