"""Benchmark: average single-token generation time — the reference's headline
metric (README "📊 Measurements": avg token time over N samples, Q40×Q80).

Prints ONE JSON line to stdout:
    {"metric": ..., "value": ms_per_token, "unit": "ms/token", "vs_baseline": x}

vs_baseline compares against the reference's best published *single-node*
Llama 2 7B number: 101.81 ms on a GCP c3d-highcpu-30 VM (BASELINE.md /
reference README.md:88). >1.0 means faster than the reference.

Decoding runs as ONE fused device program per 64 tokens (lax.scan over decode
steps, sampling on device) — the host sees one dispatch per batch of tokens,
not per token.

Model selection: Llama-2-7B shape on TPU (random bf16 weights generated on
device); set BENCH_MODEL=tiny (or run on CPU) for a TinyLlama-1.1B shape.
"""

from __future__ import annotations

import json
import os
import sys
import time


LLAMA2_7B = dict(
    arch="llama", dim=4096, hidden_dim=11008, n_layers=32, n_heads=32, n_kv_heads=32,
    vocab_size=32000, seq_len=512, head_size=128, kv_dim=4096, dtype="bfloat16",
)
TINYLLAMA_1_1B = dict(
    arch="llama", dim=2048, hidden_dim=5632, n_layers=22, n_heads=32, n_kv_heads=4,
    vocab_size=32000, seq_len=1024, head_size=64, kv_dim=256, dtype="bfloat16",
)
# the north-star model (BASELINE.json: <=5 ms/token on v5e-8); GQA 8 kv heads
LLAMA3_8B = dict(
    arch="llama", dim=4096, hidden_dim=14336, n_layers=32, n_heads=32, n_kv_heads=8,
    vocab_size=128256, seq_len=512, head_size=128, kv_dim=1024, dtype="bfloat16",
    rope_theta=500000.0,
)
# Mixtral-shape MoE scaled to one 16 GB chip (~2.6 GB q40): measures the
# selected-experts decode path (_moe_decode_selected) — the reference's
# flagship MoE capability — without a multi-chip slice. Full Mixtral-8x7B
# (~26 GB q40) needs tp>=2; this keeps the per-token expert-read ratio
# (2 of 8 experts, ~6% of weights read per token).
MIXTRAL_LITE = dict(
    arch="mixtral", dim=2048, hidden_dim=5632, n_layers=16, n_heads=16,
    n_kv_heads=8, vocab_size=32000, seq_len=512, head_size=128, kv_dim=1024,
    n_experts=8, n_active_experts=2, dtype="bfloat16",
    rope_style="half", rope_theta=1e6,  # Mixtral's half-split rotary layout
)
# Grok-1-shape MoE scaled to one chip (~2.7 GB q40): the reference's
# flagship arch — x78.38 embedding / x0.577 logit scales, post-attention +
# post-MoE norms, GELU experts, half-split rotary — at 1/8 the layer count
# and 1/2 the width so the selected-experts decode fits a 16 GB chip.
GROK1_LITE = dict(
    arch="grok1", dim=3072, hidden_dim=4096, n_layers=8, n_heads=24,
    n_kv_heads=8, vocab_size=32000, seq_len=512, head_size=128, kv_dim=1024,
    n_experts=8, n_active_experts=2, hidden_act="gelu", dtype="bfloat16",
    rope_style="half",
)

# serving-shape smoke model for the CPU-runnable continuous-batching mode:
# the scheduler comparison (continuous vs static window) is about SCHEDULING,
# not model speed, so a small fast shape keeps the staggered-arrival replay
# inside CI wall clocks while still decoding real tokens.
SMOKE_SERVE = dict(
    arch="llama", dim=256, hidden_dim=512, n_layers=4, n_heads=8,
    n_kv_heads=4, vocab_size=512, seq_len=256, head_size=32, kv_dim=128,
    dtype="float32",
)

# reference's best published single-node Llama 2 7B avg token time (ms)
BASELINE_7B_SINGLE_NODE_MS = 101.81


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _env_count(name: str) -> int:
    """An integer env knob parsed defensively, ONCE, for every consumer: a
    non-numeric or negative value counts as 0 (feature off) rather than
    raising — the bench's contract is to always end in one JSON line, and
    main()'s labeling must agree with what run_decode_bench actually ran."""
    try:
        return max(0, int(os.environ.get(name, "0") or 0))
    except ValueError:
        return 0


def _prefill_count() -> int:
    return _env_count("BENCH_PREFILL")


def _seq_override() -> int:
    return _env_count("BENCH_SEQ")


def _pct(xs, p):
    ys = sorted(xs)
    return ys[min(len(ys) - 1, int(round(p / 100.0 * (len(ys) - 1))))]


def _run_probe(code: str, sentinel: str, timeout_s: int) -> tuple:
    """Run ``code`` in a subprocess -> (ok, failure_detail). The subprocess
    matters: a down TPU tunnel makes backend init hang in native code,
    un-timeout-able in-process."""
    import subprocess
    import sys as _sys

    try:
        proc = subprocess.run(
            [_sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s}s (TPU tunnel down?)"
    if proc.returncode == 0 and sentinel in proc.stdout:
        return True, ""
    # keep BOTH streams, and keep the HEAD as well as the tail: the child's
    # early stdout sentinel (BACKEND_TPU_OK) is how callers distinguish
    # "backend reachable but the kernel failed" from "no backend at all",
    # and a tail-only truncation would eat it under any long traceback
    detail = ((proc.stdout or "") + (proc.stderr or "")).strip()
    if len(detail) > 500:
        detail = detail[:100] + " ... " + detail[-400:]
    return False, detail


def _probe_quant_kernels(kind: str = "q40", timeout_s: int = 240,
                         nosub_env: str | None = None) -> tuple:
    """Compile+run one tiny fused dequant-matmul in a subprocess.

    MUST run before this process touches the backend (some TPU runtimes are
    per-process exclusive — a child spawned after the parent holds the chip
    could silently land on CPU and validate nothing). The child asserts it is
    actually on TPU; any other platform, error, or hang returns False and the
    bench falls back to dense bf16 — slower but it always finishes.

    ``nosub_env``: force DLLAMA_Q40_NOSUB in the child, so main() can tell
    "the nosub default's correction kernel fails on this Mosaic" apart from
    "q40 kernels fail entirely" and fall back to the subtracting kernel
    instead of all the way to dense bf16.
    """
    # honor the same platform override the bench itself uses: probing the TPU
    # while the bench is forced elsewhere (or vice versa) validates nothing
    forced = os.environ.get("DLLAMA_PLATFORM")
    if forced and forced != "tpu":
        # quant kernels only earn their keep on real TPU
        return False, "platform forced off-TPU"

    code = (
        ("" if nosub_env is None else
         f"import os; os.environ['DLLAMA_Q40_NOSUB'] = {nosub_env!r}\n")
        + "import jax\n"
        + (f"jax.config.update('jax_platforms', {forced!r})\n" if forced else "")
        + "import jax.numpy as jnp\n"
        "assert jax.default_backend() == 'tpu', jax.default_backend()\n"
        "print('BACKEND_TPU_OK')\n"  # reachable; later failures are kernel-level
        "from dllama_tpu.ops import qmatmul\n"
        f"qt = qmatmul.quantize_tensor(__import__('numpy').ones((128, 128), 'float32'), {kind!r})\n"
        "y = qmatmul.matmul_any(jnp.ones((1, 128), jnp.bfloat16), qt)\n"
        "jax.block_until_ready(y)\n"
        "print('QPROBE_OK')\n"
    )
    return _run_probe(code, "QPROBE_OK", timeout_s)


def _report_lowering_failure(kernel: str, kind: str, shapes: dict,
                             detail: str) -> None:
    """Record a kernel-level Pallas lowering failure as a structured
    trajectory row instead of a log line that scrolls away.

    Called only when the probe child printed BACKEND_TPU_OK — the backend
    was reachable and compilation of OUR kernel is what died (the exact
    failure mode of BENCH_r02's (172, 4096) scale plane). The row carries
    ``error_kind="pallas_lowering"`` plus every grid/BlockSpec the launch
    would have fed Mosaic (from ops.lowering, the same planner the CPU
    gate sweeps), so the forensics never depend on scraping a truncated
    child traceback."""
    try:
        from dllama_tpu.obsv import trajectory as _traj
        from dllama_tpu.ops import lowering as _low

        try:
            plans = [p.to_dict() for p in _low.lowering_plan(kind, shapes)]
        except Exception as e:  # noqa: BLE001 — the plan itself may be what's broken
            plans = [{"plan_error": repr(e)}]
        rep = _traj.append_row(
            "kernel_lowering", "error", error=detail[-500:],
            extra={"error_kind": "pallas_lowering", "kernel": kernel,
                   "shapes": shapes, "plans": plans})
        if rep["path"]:
            log(f"pallas lowering failure recorded to {rep['path']} "
                f"(kernel={kernel})")
    except Exception:  # noqa: BLE001 — forensics must never kill the bench
        pass


def _probe_flash_kernel(timeout_s: int = 240) -> None:
    """If DLLAMA_FLASH_DECODE=1, compile+run one tiny flash-decode kernel in
    a subprocess (with the cache dtype the bench will use) BEFORE this
    process touches the backend. A Mosaic rejection — plausible for the f8
    upcast path until hardware-validated — then degrades to the dense
    attention path (flag unset, result tagged without -flash) instead of
    killing the whole 7B bench into the TinyLlama fallback."""
    if os.environ.get("DLLAMA_FLASH_DECODE", "0") != "1":
        return
    forced = os.environ.get("DLLAMA_PLATFORM")
    if forced and forced != "tpu":
        return  # off-TPU runs interpret mode; nothing to validate
    cache = ("jnp.float8_e4m3fn" if os.environ.get("BENCH_CACHE") == "f8"
             else "jnp.bfloat16")
    code = (
        "import jax\n"
        + (f"jax.config.update('jax_platforms', {forced!r})\n" if forced else "")
        + "import jax.numpy as jnp\n"
        # a non-TPU default backend (CPU-only box, no forcing env) runs the
        # kernel in interpret mode — nothing Mosaic-level to validate, so
        # SKIP (keep the flag) instead of failing and popping it: identical
        # machines must behave the same with and without DLLAMA_PLATFORM=cpu
        "if jax.default_backend() != 'tpu':\n"
        "    print('FLASH_OK (non-tpu backend: interpret mode)')\n"
        "    raise SystemExit(0)\n"
        "print('BACKEND_TPU_OK')\n"
        "from dllama_tpu.ops import flash_decode\n"
        "q = jnp.ones((1, 8, 128), jnp.bfloat16)\n"
        f"k = jnp.ones((1, 512, 4, 128), {cache})\n"
        f"v = jnp.ones((1, 512, 4, 128), {cache})\n"
        "y = flash_decode.flash_decode_attention(\n"
        "    q, k, v, jnp.int32(300), jnp.int32(0))\n"
        "jax.block_until_ready(y)\n"
        "print('FLASH_OK')\n"
    )
    ok, detail = _run_probe(code, "FLASH_OK", timeout_s)
    if not ok:
        log(f"flash-decode probe failed ({detail[:200]}); "
            "falling back to dense attention (DLLAMA_FLASH_DECODE unset)")
        if "BACKEND_TPU_OK" in detail:
            _report_lowering_failure(
                "flash_decode", "flash_decode",
                dict(T=1, L=1, S=512, n_heads=8, n_kv_heads=4, head_size=128,
                     cache_dtype=("float8_e4m3fn"
                                  if os.environ.get("BENCH_CACHE") == "f8"
                                  else "bfloat16")),
                detail)
        os.environ.pop("DLLAMA_FLASH_DECODE", None)


def _probe_q40_with_fallback() -> tuple:
    """Probe the q40 kernels as configured; if the nosub DEFAULT fails at
    the kernel level (backend demonstrably reachable — the child printed
    BACKEND_TPU_OK — and the user did not explicitly choose a variant),
    retry with the subtracting kernel and pin it for this process, so a
    Mosaic rejection of the correction kernel degrades to the slower q40
    kernel instead of all the way to dense bf16 (~3x the headline)."""
    probed, detail = _probe_quant_kernels()
    if (not probed and "BACKEND_TPU_OK" in detail
            and "DLLAMA_Q40_NOSUB" not in os.environ):
        log("nosub q40 probe failed on a live TPU; retrying with the "
            "subtracting kernel (DLLAMA_Q40_NOSUB=0)")
        _report_lowering_failure(
            "q40_matmul[nosub]", "q40",
            dict(T=1, K=128, O=128, nosub=True), detail)
        probed, detail = _probe_quant_kernels(nosub_env="0")
        if probed:
            os.environ["DLLAMA_Q40_NOSUB"] = "0"  # before any dllama import
    if not probed and "BACKEND_TPU_OK" in detail:
        _report_lowering_failure(
            "q40_matmul", "q40", dict(T=1, K=128, O=128, nosub=False), detail)
    return probed, detail


def _serving_replay(eng, mode: str, reqs: list, arrivals_s: list,
                    max_batch: int, chunk: int) -> tuple:
    """Replay ONE staggered-arrival workload -> (wall_s, latency_s, tokens).

    ``reqs`` is [(prompt_tokens, steps)]; ``arrivals_s[i]`` is request i's
    arrival offset from replay start. "continuous" admits into the resident
    slot pool between fused chunks (Engine.batch_session); "static" mimics
    the pre-continuous window batcher: run generate_batch to full drain,
    then batch whatever arrived in the meantime. latency_s[i] is request
    i's arrival-to-last-token time; tokens counts everything emitted, so
    tokens/wall_s is the aggregate serving throughput under that scheduler.
    """
    from dllama_tpu.runtime.sampler import SamplerConfig

    greedy = SamplerConfig(temperature=0.0, seed=0)
    lat = [0.0] * len(reqs)
    tokens = 0
    nxt, pending = 0, []
    t0 = time.perf_counter()
    if mode == "continuous":
        sess = eng.batch_session(max_batch, chunk=chunk)
        slot_req, emitted = {}, [0] * len(reqs)
        while nxt < len(reqs) or pending or slot_req:
            while nxt < len(reqs) and arrivals_s[nxt] <= time.perf_counter() - t0:
                pending.append(nxt)
                nxt += 1
            while pending and sess.free_slots:
                j = pending.pop(0)
                slot = sess.admit(list(reqs[j][0]), steps=reqs[j][1],
                                  sampler=greedy)
                slot_req[slot] = j
            if not slot_req:
                # pool empty and the next request is not due yet: idle wait
                time.sleep(max(0.0, arrivals_s[nxt] - (time.perf_counter() - t0)))
                continue
            for slot, burst in sess.step_chunk().items():
                j = slot_req[slot]
                emitted[j] += len(burst)
                if sess.is_done(slot):
                    lat[j] = (time.perf_counter() - t0) - arrivals_s[j]
                    tokens += emitted[j]
                    sess.release(slot)
                    del slot_req[slot]
        sess.close()
    else:
        while nxt < len(reqs) or pending:
            while nxt < len(reqs) and arrivals_s[nxt] <= time.perf_counter() - t0:
                pending.append(nxt)
                nxt += 1
            if not pending:
                time.sleep(max(0.0, arrivals_s[nxt] - (time.perf_counter() - t0)))
                continue
            group, pending = pending[:max_batch], pending[max_batch:]
            rows = eng.generate_batch(
                [list(reqs[j][0]) for j in group],
                steps=max(reqs[j][1] for j in group),
                sampler=greedy,
                row_steps=[reqs[j][1] for j in group])
            end = time.perf_counter() - t0
            for j, row in zip(group, rows):
                lat[j] = end - arrivals_s[j]
                tokens += min(len(row), reqs[j][1])
    return time.perf_counter() - t0, lat, tokens


def run_decode_bench(cfg_dict: dict, bench_steps: int = None, quant_ok: bool = False):
    """``bench_steps`` trades compile time against timing fidelity: the whole
    run is ONE dispatch + ONE host sync, and on a tunneled TPU that sync has
    a fixed ~70 ms floor — 256 tokens (the TPU default) dilute it to
    ~0.3 ms/token where 64 would smear in ~1.1. Off-TPU (CI smoke) the
    default stays 64: CPU steps are slow and nothing is being measured.
    Returns (best ms/token, weights_kind_used)."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.models import llama
    from dllama_tpu.models.config import ModelConfig
    from dllama_tpu.runtime.generate import Engine
    from dllama_tpu.runtime.sampler import SamplerConfig

    if bench_steps is None:
        bench_steps = _env_count("BENCH_STEPS") or (
            256 if jax.default_backend() == "tpu" else 64
        )
    # BENCH_SEQ=N overrides the context length: decode attention is a
    # static-shape masked read of the WHOLE cache every step, so this
    # measures long-context per-token cost directly (pair with
    # BENCH_CACHE=f8, which halves exactly the bytes this knob adds)
    seq = _seq_override()
    if seq:
        cfg_dict = dict(cfg_dict, seq_len=seq)
    cfg = ModelConfig(**cfg_dict)
    # config tag shared by EVERY return path, so the result record always
    # states the seq/cache configuration it was measured under
    cfg_tag = (f"-seq{seq}" if seq else "") + (
        "-f8cache" if os.environ.get("BENCH_CACHE") == "f8" else "")
    n_dev = len(jax.devices())
    mesh = None
    batch = _env_count("BENCH_BATCH")
    if n_dev > 1 and cfg.n_kv_heads % n_dev == 0:
        from dllama_tpu.parallel.mesh import tp_mesh

        mesh = tp_mesh(n_dev)
        log(f"tensor-parallel over {n_dev} devices")

    # Q40 weights by default on TPU: the baseline numbers are Q40xQ80 runs,
    # and the fused dequant-matmul kernels keep 4-bit weights resident in HBM
    # (4x less weight traffic per token) — including under TP, where the
    # quant planes shard over the mesh (parallel.quant_tp), the reference's
    # production Q40-on-every-node configuration. BENCH_WEIGHTS=bf16|q80
    # overrides. Off-TPU the Pallas kernels run in interpret mode (orders of
    # magnitude slower), so bf16 is the default there.
    # quant_ok comes from the pre-backend-init subprocess probe in main().
    default_weights = "q40" if jax.default_backend() == "tpu" and quant_ok else "bf16"
    weights = os.environ.get("BENCH_WEIGHTS", default_weights)
    log(f"building params on device: dim={cfg.dim} layers={cfg.n_layers} ({weights})")
    # with a mesh, dense params are written directly into their shards — no
    # chip ever holds the full model
    if weights in ("q40", "q80"):
        params = llama.device_random_quant_params(cfg, kind=weights, seed=0)
    else:
        params = llama.device_random_params(cfg, seed=0, mesh=mesh)
    jax.block_until_ready(params)
    # decode_chunk=bench_steps: ONE device dispatch + host sync for the whole
    # timed run — the tunnel's host round trip (~70 ms on the axon box) would
    # otherwise smear ~1 ms/token into a 64-chunk measurement
    # BENCH_CACHE=f8 stores the KV cache as float8_e4m3fn (half the cache
    # read traffic; ~2% of 7B decode bytes at seq 512, more at long context)
    cache_dtype = (jnp.float8_e4m3fn if os.environ.get("BENCH_CACHE") == "f8"
                   else jnp.bfloat16)
    eng = Engine(cfg, params, SamplerConfig(temperature=0.0), cache_dtype=cache_dtype,
                 mesh=mesh, decode_chunk=bench_steps)
    # -flash tag, computed ONCE for every decode return path from the SAME
    # gate the model layer uses (flash_decode.engages) PLUS the engine-path
    # condition: the dense-pjit mesh branch pins allow_flash=False (Pallas
    # calls don't partition under pjit), so a dense-weights multi-device
    # run must not be labeled -flash. The -subkernel tag reads the LATCHED
    # qmatmul.Q40_NOSUB gate the kernels dispatched on (explicit opt-out OR
    # the probe's nosub-rejection fallback).
    from dllama_tpu.ops import flash_decode, qmatmul as _qmatmul

    flash_possible = mesh is None or weights in ("q40", "q80")
    flash_tag = "-flash" if (flash_possible and flash_decode.engages(
        1, cfg.seq_len, cache_dtype)) else ""
    if weights == "q40" and not _qmatmul.Q40_NOSUB:
        cfg_tag += "-subkernel"
    # Engine may have fused the projection matrices into new buffers; drop
    # this frame's reference so the unfused originals free immediately
    del params

    # BENCH_PREFILL=N replays the PREFILL STALL: a near-max-length N-token
    # prompt is admitted into a pool whose resident rows are mid-decode, and
    # the measurement is the residents' INTER-TOKEN GAP — monolithic
    # admission stalls every resident for the whole prefill, chunked
    # admission (admit_begin + one prefill_step per tick) bounds the stall
    # to one prefill piece plus one decode chunk. A capacity phase counts
    # rows resident at the SAME modeled HBM budget with uniform vs bucketed
    # slot KV. CPU-runnable (BENCH_MODEL=smoke); the gate FAILS the bench if
    # a chunked-mode resident gap exceeds 2x the per-tick chunk budget, or
    # if bucketed pools don't admit strictly more short rows than uniform.
    # BENCH_PREFILL_CHUNK overrides the piece size (default chunk * pool);
    # BENCH_PREFILL_OUT writes the full report JSON for CI artifacts.
    pf = _prefill_count()
    if pf:
        import numpy as np

        S = cfg.seq_len
        pf = min(pf, S - 1)
        B = max(2, min(batch or 4, 8))
        chunk = 8
        pchunk = _env_count("BENCH_PREFILL_CHUNK") or chunk * B
        rng = np.random.default_rng(0)
        long_prompt = [int(t) for t in rng.integers(1, cfg.vocab_size, pf)]
        res_prompt = [int(t) for t in rng.integers(1, cfg.vocab_size, 6)]
        greedy = SamplerConfig(temperature=0.0, seed=0)
        res_steps = (S - len(res_prompt)) // chunk * chunk
        new_steps = 2 * chunk

        def _stall_replay(chunked):
            """One admission of the long prompt into a busy pool; returns
            (resident gaps ms, decode tick ms, prefill piece ms)."""
            sess = eng.batch_session(
                B, chunk=chunk, prefill_chunk=pchunk if chunked else 0)
            residents = [sess.admit(list(res_prompt), steps=res_steps,
                                    sampler=greedy) for _ in range(B - 1)]
            last, gaps, ticks, pieces = {}, [], [], []

            def tick():
                t0 = time.perf_counter()
                fresh = sess.step_chunk()
                now = time.perf_counter()
                ticks.append((now - t0) * 1000.0)
                for h in residents:
                    if fresh.get(h):
                        if h in last:
                            gaps.append((now - last[h]) * 1000.0)
                        last[h] = now

            tick()  # anchor every resident's clock...
            tick()  # ...and record one steady-state gap before the stall
            if chunked:
                nh = sess.admit_begin(long_prompt, steps=new_steps,
                                      sampler=greedy)
                while not sess.is_done(nh):
                    t0 = time.perf_counter()
                    if sess.prefill_step() is not None:
                        pieces.append((time.perf_counter() - t0) * 1000.0)
                    tick()
            else:
                nh = sess.admit(long_prompt, steps=new_steps, sampler=greedy)
                while not sess.is_done(nh):
                    tick()
            sess.close()
            return gaps, ticks, pieces

        def _capacity(bucketed):
            """Rows admitted before the modeled budget (B * seq_len KV
            token-slots — identical both ways) says no. 1-token prompts:
            the shortest request, where bucketing's win is largest."""
            sess = eng.batch_session(B, chunk=chunk, bucket_kv=bucketed,
                                     min_bucket=16)
            n = 0
            while sess.can_admit(1, chunk) and n < 4096:
                sess.admit_begin([1], steps=chunk, sampler=greedy)
                n += 1
            sess.close()
            return n

        log(f"prefill stall replay: {pf}-token prompt into a busy pool "
            f"(B={B}, chunk={chunk}, prefill_chunk={pchunk}); warmup...")
        t0 = time.perf_counter()
        _stall_replay(True)  # compiles pool decode + every prefill bucket
        _stall_replay(False)
        log(f"warmup done in {time.perf_counter() - t0:.1f}s")
        mono_gaps, _, _ = _stall_replay(False)
        ch_gaps, ch_ticks, ch_pieces = _stall_replay(True)
        budget_ms = _pct(ch_pieces, 50) + _pct(ch_ticks, 50)
        gate_ms = 2.0 * budget_ms
        mono_p99, ch_p99 = _pct(mono_gaps, 99), _pct(ch_gaps, 99)
        log(f"resident inter-token gap p99: monolithic {mono_p99:.1f} ms "
            f"vs chunked {ch_p99:.1f} ms (worst {max(ch_gaps):.1f} ms; "
            f"tick budget {budget_ms:.1f} ms, gate {gate_ms:.1f} ms)")
        rows_uni, rows_bkt = _capacity(False), _capacity(True)
        log(f"rows resident at fixed HBM budget ({B * S} KV token-slots): "
            f"uniform {rows_uni} vs bucketed {rows_bkt}")
        report = {
            "prompt_tokens": pf, "pool": B, "decode_chunk": chunk,
            "prefill_chunk": pchunk,
            "monolithic_gap_p99_ms": round(mono_p99, 3),
            "chunked_gap_p99_ms": round(ch_p99, 3),
            "chunked_gap_max_ms": round(max(ch_gaps), 3),
            "tick_budget_ms": round(budget_ms, 3),
            "gate_ms": round(gate_ms, 3),
            "budget_kv_tokens": B * S,
            "rows_uniform": rows_uni, "rows_bucketed": rows_bkt,
        }
        out_path = os.environ.get("BENCH_PREFILL_OUT")
        if out_path:
            with open(out_path, "w") as f:
                json.dump(report, f, indent=2)
            log(f"report written to {out_path}")
        if ch_p99 > gate_ms:
            raise RuntimeError(
                f"chunked prefill left a resident-row gap of {ch_p99:.1f} "
                f"ms p99, over the 2x chunk budget gate of {gate_ms:.1f} "
                f"ms: {report}")
        if rows_bkt <= rows_uni:
            raise RuntimeError(
                f"bucketed slot KV admitted {rows_bkt} rows vs uniform "
                f"{rows_uni} at the same budget — must be strictly more: "
                f"{report}")
        return ch_p99, f"{weights}-prefillstall{pf}-b{B}{cfg_tag}"

    # BENCH_PREFIX=N replays a SHARED-SYSTEM-PROMPT workload through the
    # paged-KV radix prefix cache: N sequential requests whose prompts are
    # one seq_len/2 system prefix plus a short unique tail (>=50% shared),
    # measured as per-request TTFT (admit -> first token). The cold control
    # replays the SAME lengths with fully unique prompts, so every
    # admission pays full prefill. A capacity phase counts 1-token rows
    # resident at the same modeled HBM budget paged vs uniform. CPU-runnable
    # (BENCH_MODEL=smoke); the gate FAILS the bench unless warm TTFT p50 is
    # strictly below cold, paged rows >= uniform rows, and the paged
    # replays performed ZERO slab-migration copies (growth appends a page).
    # BENCH_PREFIX_PAGE overrides the page size (default 16 tokens);
    # BENCH_PREFIX_OUT writes the full report JSON for CI artifacts.
    px = _env_count("BENCH_PREFIX")
    if px:
        import numpy as np

        S = cfg.seq_len
        n_req = max(4, min(px, 64))
        B = max(2, min(batch or 4, 8))
        chunk = 8
        page = _env_count("BENCH_PREFIX_PAGE") or 16
        rng = np.random.default_rng(0)
        shared = [int(t) for t in rng.integers(1, cfg.vocab_size, S // 2)]
        tail_len = max(4, S // 16)
        greedy = SamplerConfig(temperature=0.0, seed=0)

        def _prompts(share):
            out = []
            for i in range(n_req):
                r = np.random.default_rng((1 if share else 100) + i)
                tail = [int(t) for t in r.integers(1, cfg.vocab_size,
                                                   tail_len)]
                head = shared if share else [
                    int(t) for t in r.integers(1, cfg.vocab_size,
                                               len(shared))]
                out.append(head + tail)
            return out

        def _ttft_replay(share):
            """Sequential replay; returns (per-request TTFT ms, migrations,
            prefix hit rate, evictions). A fresh session per replay: the
            radix cache starts cold both ways."""
            sess = eng.batch_session(B, chunk=chunk, prefill_chunk=4 * chunk,
                                     kv_pages=page)
            ttfts = []
            for prompt in _prompts(share):
                t0 = time.perf_counter()
                h = sess.admit_begin(prompt, steps=chunk, sampler=greedy)
                got = []
                while not got and not sess.is_done(h):
                    sess.prefill_step()
                    got.extend(sess.step_chunk().get(h, []))
                ttfts.append((time.perf_counter() - t0) * 1000.0)
                while not sess.is_done(h):
                    sess.prefill_step()
                    sess.step_chunk()
                sess.release(h)
            stats = (sess.migrations, sess.prefix_hit_rate,
                     sess.prefix_evictions)
            sess.close()
            return (ttfts,) + stats

        def _capacity(paged):
            """1-token rows admitted at the same modeled budget (B * seq_len
            KV token-slots): paged reserves ceil(need/page) pages per row,
            uniform burns a full-context slab row regardless."""
            sess = eng.batch_session(B, chunk=chunk,
                                     kv_pages=page if paged else 0)
            n = 0
            while sess.can_admit(1, chunk, [1]) and n < 4096:
                sess.admit_begin([1], steps=chunk, sampler=greedy)
                n += 1
            migr = getattr(sess, "migrations", 0)
            sess.close()
            return n, migr

        log(f"prefix cache replay: {n_req} requests, {len(shared)}-token "
            f"shared prefix + {tail_len}-token tails (page={page}); warmup...")
        t0 = time.perf_counter()
        _ttft_replay(True)  # compiles prefill pieces + paged decode groups
        log(f"warmup done in {time.perf_counter() - t0:.1f}s")
        cold_ttfts, cold_migr, _, _ = _ttft_replay(False)
        warm_ttfts, warm_migr, hit_rate, evictions = _ttft_replay(True)
        warm = warm_ttfts[1:]  # request 0 seeds the cache: it IS the cold path
        cold = cold_ttfts
        warm_p50, warm_p99 = _pct(warm, 50), _pct(warm, 99)
        cold_p50, cold_p99 = _pct(cold, 50), _pct(cold, 99)
        log(f"TTFT p50: cold {cold_p50:.1f} ms vs warm {warm_p50:.1f} ms "
            f"(p99 {cold_p99:.1f} vs {warm_p99:.1f}; hit rate "
            f"{hit_rate:.2f}, {evictions} evictions)")
        rows_uni, _ = _capacity(False)
        rows_paged, cap_migr = _capacity(True)
        log(f"rows resident at fixed HBM budget ({B * S} KV token-slots): "
            f"uniform {rows_uni} vs paged {rows_paged}")
        report = {
            "requests": n_req, "shared_tokens": len(shared),
            "tail_tokens": tail_len, "page_tokens": page, "pool": B,
            "cold_ttft_p50_ms": round(cold_p50, 3),
            "cold_ttft_p99_ms": round(cold_p99, 3),
            "warm_ttft_p50_ms": round(warm_p50, 3),
            "warm_ttft_p99_ms": round(warm_p99, 3),
            "prefix_hit_rate": round(hit_rate, 4),
            "prefix_evictions": evictions,
            "budget_kv_tokens": B * S,
            "rows_uniform": rows_uni, "rows_paged": rows_paged,
            "migrations": cold_migr + warm_migr + cap_migr,
        }
        out_path = os.environ.get("BENCH_PREFIX_OUT")
        if out_path:
            with open(out_path, "w") as f:
                json.dump(report, f, indent=2)
            log(f"report written to {out_path}")
        if warm_p50 >= cold_p50:
            raise RuntimeError(
                f"warm (prefix-cached) TTFT p50 {warm_p50:.1f} ms is not "
                f"below cold {cold_p50:.1f} ms on >=50%-shared traffic: "
                f"{report}")
        if rows_paged < rows_uni:
            raise RuntimeError(
                f"paged KV admitted {rows_paged} rows vs uniform "
                f"{rows_uni} at the same budget — must not be fewer: "
                f"{report}")
        if report["migrations"] != 0:
            raise RuntimeError(
                f"paged mode performed {report['migrations']} slab "
                f"migration copies — growth must append pages: {report}")
        return warm_p50, f"{weights}-prefix{n_req}-pg{page}{cfg_tag}"

    # BENCH_OVERLAP=N replays ONE N-request mix through a real pooled
    # BatchSession TWICE on the same TP mesh + quant weights — monolithic
    # shard_map programs vs the microbatch compute/communication-overlap
    # programs (--tp-overlap) — and reports the A/B wall-clock delta. The
    # mode is EXACT by construction, so the replay FAILS unless the two
    # runs stream bit-identical tokens AND the overlap engine actually
    # engaged (dllama_tp_overlap_chunks_total moved; >= 2 resident rows).
    # CPU-runnable (BENCH_MODEL=smoke + the CI lanes' 8 virtual devices):
    # off-TPU the delta is plumbing-only — the ring-vs-fused gather win is
    # an ICI property, so TPU numbers are owed for any perf claim.
    # BENCH_OVERLAP_OUT writes the full report JSON for CI artifacts.
    ovn = _env_count("BENCH_OVERLAP")
    if ovn:
        import numpy as np

        from dllama_tpu import observability
        from dllama_tpu.parallel.mesh import tp_mesh

        # the serving smoke shape has n_kv_heads=4: pick the largest TP
        # degree the head count supports instead of requiring n_dev | kv
        tp = n_dev
        while tp > 1 and cfg.n_kv_heads % tp:
            tp -= 1
        if tp < 2:
            raise RuntimeError(
                "BENCH_OVERLAP needs a TP mesh (run on >1 device, or CPU "
                "with XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        ov_mesh = tp_mesh(tp)
        qkind = weights if weights in ("q40", "q80") else "q40"
        log(f"overlap A/B: tp={tp}, {qkind} weights, building engines...")
        qparams = llama.device_random_quant_params(cfg, kind=qkind, seed=0)
        reg = observability.MetricsRegistry()
        greedy = SamplerConfig(temperature=0.0, seed=0)
        e_mono = Engine(cfg, qparams, greedy, cache_dtype=cache_dtype,
                        mesh=ov_mesh, metrics=None)
        e_ov = Engine(cfg, qparams, greedy, cache_dtype=cache_dtype,
                      mesh=ov_mesh, tp_overlap=True, metrics=reg)
        if not e_ov.tp_overlap_active:
            raise RuntimeError(
                f"overlap engine did not come up overlapped: "
                f"{e_ov.tp_overlap_reason}")

        n_req = max(4, min(ovn, 64))
        B = max(2, min(batch or 4, 8))
        chunk = 8
        rng = np.random.default_rng(0)
        reqs = []
        for i in range(n_req):
            plen = int(rng.integers(4, max(8, cfg.seq_len // 8)))
            steps = chunk * int(rng.integers(1, 4))
            prompt = [int(t) for t in rng.integers(1, cfg.vocab_size, plen)]
            reqs.append((prompt, steps))

        def _overlap_replay(eng):
            """Admit-all pooled drain -> (wall_s, tokens, [streams])."""
            sess = eng.batch_session(B, chunk=chunk)
            got = {}
            pending = list(range(n_req))
            handle_req = {}
            t0 = time.perf_counter()
            while pending or handle_req:
                while pending and sess.free_slots:
                    j = pending.pop(0)
                    h = sess.admit(list(reqs[j][0]), steps=reqs[j][1],
                                   sampler=greedy)
                    handle_req[h] = j
                for h, burst in sess.step_chunk().items():
                    got.setdefault(handle_req[h], []).extend(burst)
                    if sess.is_done(h):
                        sess.release(h)
                        del handle_req[h]
            wall = time.perf_counter() - t0
            sess.close()
            streams = [got[j] for j in range(n_req)]
            return wall, sum(len(s) for s in streams), streams

        def _chunks(registry):
            for line in registry.render().splitlines():
                if line.startswith("dllama_tp_overlap_chunks_total"):
                    return float(line.split()[-1])
            return 0.0

        _overlap_replay(e_mono)  # compile both ways before timing
        _overlap_replay(e_ov)
        engaged_at = _chunks(reg)
        mono_wall, mono_tok, mono_streams = _overlap_replay(e_mono)
        ov_wall, ov_tok, ov_streams = _overlap_replay(e_ov)
        engaged = _chunks(reg) - engaged_at
        if ov_streams != mono_streams:
            diff = [j for j in range(n_req)
                    if ov_streams[j] != mono_streams[j]]
            raise RuntimeError(
                f"overlap replay diverged from monolithic on request(s) "
                f"{diff} — the mode must be bit-identical")
        if engaged <= 0:
            raise RuntimeError(
                "overlap programs never engaged during the timed replay "
                "(dllama_tp_overlap_chunks_total did not move)")
        delta_pct = (mono_wall - ov_wall) / mono_wall * 100.0
        log(f"monolithic {mono_tok / mono_wall:.1f} tok/s "
            f"({mono_wall:.2f}s) vs overlap {ov_tok / ov_wall:.1f} tok/s "
            f"({ov_wall:.2f}s): {delta_pct:+.1f}% wall "
            f"({engaged:.0f} overlapped dispatches)")
        on_tpu = jax.default_backend() == "tpu"
        if not on_tpu:
            log("CPU smoke: delta is plumbing-only — ring-vs-fused gather "
                "wins need ICI; TPU numbers owed")
        report = {
            "requests": n_req, "pool": B, "tp": tp, "weights": qkind,
            "wire": e_ov.tp_wire, "tokens": mono_tok,
            "mono_wall_s": round(mono_wall, 3),
            "overlap_wall_s": round(ov_wall, 3),
            "mono_tok_s": round(mono_tok / mono_wall, 2),
            "overlap_tok_s": round(ov_tok / ov_wall, 2),
            "delta_pct": round(delta_pct, 2),
            "overlap_chunks": engaged,
            "bit_identical": True,
            "backend": jax.default_backend(),
            "tpu_deltas_owed": not on_tpu,
        }
        if not on_tpu:
            report["note"] = ("CPU smoke: structural gates only (bit-"
                              "identity + engagement); throughput deltas "
                              "owed to the TPU battery — the ring-vs-fused "
                              "gather win is an ICI property")
        out_path = os.environ.get("BENCH_OVERLAP_OUT")
        if out_path:
            with open(out_path, "w") as f:
                json.dump(report, f, indent=2)
            log(f"report written to {out_path}")
        return (ov_wall / max(ov_tok, 1)) * 1000.0, \
            f"{qkind}-overlap{n_req}-tp{tp}{cfg_tag}"

    # BENCH_REDUCE=N replays ONE N-request mix through a real pooled
    # BatchSession on the same TP mesh + quant weights THREE ways —
    # gather-only baseline, --tp-reduce plain (row-parallel wo/w2 over the
    # pinned-order ring reduce-scatter), and --tp-reduce q80 (each hop's
    # payload block-quantized) — and gates on the mode's contract: both
    # row modes must replay DETERMINISTICALLY (the pinned ring order) and
    # actually engage (dllama_tp_reduce_chunks_total moved), plain must
    # agree with the baseline streams modulo a bounded handful of greedy
    # near-tie flips (the K-split matmul reassociates the f32 sum), and
    # the analytic per-layer wire model at 7B shapes must come out
    # STRICTLY below the gather-only schedule for the q80 reduce. CPU-runnable (BENCH_MODEL=smoke + the CI lanes' 8
    # virtual devices): off-TPU the wall delta is plumbing-only — the
    # reduce-scatter win is an ICI property, so TPU deltas are owed in the
    # trajectory. BENCH_REDUCE_OUT writes the report JSON for CI.
    redn = _env_count("BENCH_REDUCE")
    if redn:
        import numpy as np

        from dllama_tpu import observability
        from dllama_tpu.parallel.mesh import tp_mesh
        from dllama_tpu.parallel.quant_tp import validate_tp_reduce
        from dllama_tpu.runtime.generate import dense_stack_wire_feat_bytes

        tp = n_dev
        while tp > 1 and cfg.n_kv_heads % tp:
            tp -= 1
        if tp < 2:
            raise RuntimeError(
                "BENCH_REDUCE needs a TP mesh (run on >1 device, or CPU "
                "with XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        qkind = weights if weights in ("q40", "q80") else "q40"
        while tp > 1 and validate_tp_reduce(cfg, qkind, tp) is not None:
            tp //= 2  # shard-granularity misfit at this degree
        if tp < 2:
            raise RuntimeError(
                f"BENCH_REDUCE: no tp degree satisfies the {qkind} "
                f"row-shard granularity at dim={cfg.dim}")
        red_mesh = tp_mesh(tp)
        log(f"reduce A/B/C: tp={tp}, {qkind} weights, building engines...")
        qparams = llama.device_random_quant_params(cfg, kind=qkind, seed=0)
        greedy = SamplerConfig(temperature=0.0, seed=0)
        e_base = Engine(cfg, qparams, greedy, cache_dtype=cache_dtype,
                        mesh=red_mesh, metrics=None)
        engines = {}
        regs = {}
        for mode in ("plain", "q80"):
            regs[mode] = observability.MetricsRegistry()
            engines[mode] = Engine(
                cfg, qparams, greedy, cache_dtype=cache_dtype,
                mesh=red_mesh, tp_reduce=mode, metrics=regs[mode])
            if not engines[mode].tp_reduce_active:
                raise RuntimeError(
                    f"tp_reduce={mode} engine did not come up row-parallel: "
                    f"{engines[mode].tp_reduce_reason}")

        n_req = max(4, min(redn, 64))
        B = max(2, min(batch or 4, 8))
        chunk = 8
        rng = np.random.default_rng(0)
        reqs = []
        for i in range(n_req):
            plen = int(rng.integers(4, max(8, cfg.seq_len // 8)))
            steps = chunk * int(rng.integers(1, 4))
            prompt = [int(t) for t in rng.integers(1, cfg.vocab_size, plen)]
            reqs.append((prompt, steps))

        def _reduce_replay(eng):
            """Admit-all pooled drain -> (wall_s, tokens, [streams])."""
            sess = eng.batch_session(B, chunk=chunk)
            got = {}
            pending = list(range(n_req))
            handle_req = {}
            t0 = time.perf_counter()
            while pending or handle_req:
                while pending and sess.free_slots:
                    j = pending.pop(0)
                    h = sess.admit(list(reqs[j][0]), steps=reqs[j][1],
                                   sampler=greedy)
                    handle_req[h] = j
                for h, burst in sess.step_chunk().items():
                    got.setdefault(handle_req[h], []).extend(burst)
                    if sess.is_done(h):
                        sess.release(h)
                        del handle_req[h]
            wall = time.perf_counter() - t0
            sess.close()
            streams = [got[j] for j in range(n_req)]
            return wall, sum(len(s) for s in streams), streams

        def _red_chunks(registry):
            for line in registry.render().splitlines():
                if line.startswith("dllama_tp_reduce_chunks_total"):
                    return float(line.split()[-1])
            return 0.0

        _reduce_replay(e_base)  # compile all three before timing
        for mode in ("plain", "q80"):
            _reduce_replay(engines[mode])
        engaged_at = {m: _red_chunks(regs[m]) for m in regs}
        base_wall, base_tok, base_streams = _reduce_replay(e_base)
        walls, toks = {}, {}
        walls["plain"], toks["plain"], plain_streams = \
            _reduce_replay(engines["plain"])
        walls["q80"], toks["q80"], q80_streams = \
            _reduce_replay(engines["q80"])
        _, _, plain_again = _reduce_replay(engines["plain"])
        _, _, q80_again = _reduce_replay(engines["q80"])
        engaged = {m: _red_chunks(regs[m]) - engaged_at[m] for m in regs}
        # the ring's bitwise guarantee is the PINNED ORDER (reproducible
        # run to run — gated hard below); vs the gather-only baseline the
        # K-split matmul legitimately reassociates the f32 sum, so a
        # greedy near-tie can flip a token on rare requests. Plain must
        # therefore match the baseline on all but a bounded few requests
        # (same lengths always), not bitwise on every stream — the
        # bitwise schedule property itself is pinned by
        # tests/test_tp_reduce.py against a numpy reference.
        if plain_again != plain_streams or q80_again != q80_streams:
            raise RuntimeError(
                "row-parallel replay is not deterministic — the ring "
                "order is pinned, so identical replays must stream "
                "identical tokens")
        for mode, streams in (("plain", plain_streams),
                              ("q80", q80_streams)):
            if [len(s) for s in streams] != [len(s) for s in base_streams]:
                raise RuntimeError(
                    f"{mode} row-parallel replay lost/added tokens "
                    f"vs baseline")
        plain_flips = [j for j in range(n_req)
                       if plain_streams[j] != base_streams[j]]
        if len(plain_flips) > max(1, n_req // 4):
            raise RuntimeError(
                f"plain row-parallel replay diverged from gather-only on "
                f"{len(plain_flips)}/{n_req} request(s) {plain_flips} — "
                f"beyond near-tie reassociation flips; row matmuls wrong?")
        if plain_flips:
            log(f"plain row replay: {len(plain_flips)}/{n_req} request(s) "
                f"flipped a greedy near-tie vs baseline "
                f"(f32 reassociation): {plain_flips}")
        for mode in ("plain", "q80"):
            if engaged[mode] <= 0:
                raise RuntimeError(
                    f"tp_reduce={mode} programs never engaged during the "
                    f"timed replay (dllama_tp_reduce_chunks_total "
                    f"did not move)")
        # analytic per-layer wire model at 7B shapes (q80-compressed
        # gathers both sides, the deployed configuration): the q80 reduce
        # must model strictly below the gather-only schedule. The plain
        # f32 reduce does NOT (its reduce hops are 4 B/feature vs the
        # baseline's 1.125 B/feature hidden gather) — it is the
        # bit-reproducibility mode, not the bandwidth mode.
        cfg7 = type("", (), {"n_layers": 32, "dim": 4096})()
        hidden7 = 11008
        base7 = dense_stack_wire_feat_bytes(cfg7, hidden7, 1.125)
        row7 = dense_stack_wire_feat_bytes(cfg7, hidden7, 1.125, "q80")
        if row7 >= base7:
            raise RuntimeError(
                f"modeled 7B bytes-on-wire per token: row-parallel q80 "
                f"{row7:.0f} is not below gather-only {base7:.0f}")
        log(f"modeled 7B wire/token: gather-only {base7 / 1e3:.1f} KB vs "
            f"row+q80 reduce {row7 / 1e3:.1f} KB "
            f"({(1 - row7 / base7) * 100.0:+.1f}% saved)")
        for mode in ("plain", "q80"):
            log(f"baseline {base_tok / base_wall:.1f} tok/s "
                f"({base_wall:.2f}s) vs tp_reduce={mode} "
                f"{toks[mode] / walls[mode]:.1f} tok/s "
                f"({walls[mode]:.2f}s): "
                f"{(base_wall - walls[mode]) / base_wall * 100.0:+.1f}% "
                f"wall ({engaged[mode]:.0f} row dispatches)")
        on_tpu = jax.default_backend() == "tpu"
        if not on_tpu:
            log("CPU smoke: structural gates only (determinism, engagement, "
                "bounded plain flips, wire model); TPU deltas owed")
        report = {
            "requests": n_req, "pool": B, "tp": tp, "weights": qkind,
            "tokens": base_tok,
            "base_wall_s": round(base_wall, 3),
            "plain_wall_s": round(walls["plain"], 3),
            "q80_wall_s": round(walls["q80"], 3),
            "base_tok_s": round(base_tok / base_wall, 2),
            "plain_tok_s": round(toks["plain"] / walls["plain"], 2),
            "q80_tok_s": round(toks["q80"] / walls["q80"], 2),
            "plain_near_tie_flips": len(plain_flips),
            "deterministic": True,
            "reduce_chunks_plain": engaged["plain"],
            "reduce_chunks_q80": engaged["q80"],
            "wire_kb_token_smoke_base": round(e_base.wire_kb(1), 3),
            "wire_kb_token_smoke_q80": round(engines["q80"].wire_kb(1), 3),
            "modeled_7b_wire_base_kb": round(base7 / 1e3, 2),
            "modeled_7b_wire_row_q80_kb": round(row7 / 1e3, 2),
            "modeled_7b_wire_saved_pct": round((1 - row7 / base7) * 100, 2),
            "backend": jax.default_backend(),
            "tpu_deltas_owed": not on_tpu,
        }
        if not on_tpu:
            report["note"] = ("CPU smoke: structural gates only — the "
                              "reduce-scatter bandwidth win is an ICI "
                              "property, TPU deltas owed to the battery")
        out_path = os.environ.get("BENCH_REDUCE_OUT")
        if out_path:
            with open(out_path, "w") as f:
                json.dump(report, f, indent=2)
            log(f"report written to {out_path}")
        return (walls["q80"] / max(toks["q80"], 1)) * 1000.0, \
            f"{qkind}-reduce{n_req}-tp{tp}{cfg_tag}"

    # BENCH_CONTINUOUS=N replays a staggered-arrival serving workload of N
    # requests through BOTH schedulers — the continuous slot pool
    # (Engine.batch_session: rows admitted mid-flight between fused chunks)
    # and the old static window batcher (generate_batch run to full drain,
    # then re-batch the queue) — and reports aggregate tok/s plus
    # per-request latency for each. Every third request gets a 4x budget:
    # that is the static pathology (short rows queue behind the long row's
    # drain) continuous batching exists to remove. CPU-runnable; pair with
    # BENCH_MODEL=smoke off-TPU so the replay fits a CI wall clock.
    cont = _env_count("BENCH_CONTINUOUS")
    if cont:
        rng_c = __import__("numpy").random.default_rng(2)
        prompt = [int(t) for t in rng_c.integers(1, cfg.vocab_size, 6)]
        B = min(max(2, batch or 4), cont)
        chunk = 8
        # budgets in whole chunks so every decode dispatch compiles at ONE
        # n_steps; same prompt length -> one prefill bucket
        base = max(chunk, bench_steps // 4 // chunk * chunk)
        cap = (cfg.seq_len - len(prompt)) // chunk * chunk
        reqs = [(prompt, min(cap, 4 * base if i % 3 == 2 else base))
                for i in range(cont)]
        log(f"continuous-batching replay: {cont} requests, pool={B}, "
            f"chunk={chunk}, budgets {base}/{min(cap, 4 * base)}")
        old_chunk = eng.decode_chunk
        eng.decode_chunk = chunk  # static batcher drains at the same grain
        greedy = SamplerConfig(temperature=0.0, seed=0)
        # warmup compiles every shape either replay can hit — the pool's
        # (B, chunk) decode loop, the single-row prefill bucket, and each
        # static group size 1..B — and times one resident chunk to set a
        # near-capacity arrival gap (pool service rate ~1 request/chunk at
        # these budgets; 1.5 chunks/arrival -> ~0.7 utilization)
        log("warmup (compile: pool chunk + static group sizes)...")
        t0 = time.perf_counter()
        sess = eng.batch_session(B, chunk=chunk)
        s0 = sess.admit(list(prompt), steps=3 * chunk, sampler=greedy)
        sess.step_chunk()  # first chunk pays the compile; don't time it
        t1 = time.perf_counter()
        sess.step_chunk()
        chunk_s = time.perf_counter() - t1
        while not sess.is_done(s0):
            sess.step_chunk()
        sess.close()
        for b in range(1, B + 1):
            eng.generate_batch([list(prompt)] * b, steps=chunk,
                               sampler=greedy)
        log(f"warmup done in {time.perf_counter() - t0:.1f}s "
            f"({chunk_s * 1000:.0f} ms/resident chunk)")
        arrivals = [i * 1.5 * chunk_s for i in range(cont)]
        results = {}
        for mode in ("static", "continuous"):
            wall, lats, toks = _serving_replay(eng, mode, reqs, arrivals,
                                               B, chunk)
            results[mode] = (wall, toks)
            ms_sorted = sorted(x * 1000.0 for x in lats)
            log(f"{mode:>10}: {toks} tokens in {wall:.2f}s = "
                f"{toks / wall:.1f} tok/s aggregate | request latency mean "
                f"{sum(ms_sorted) / len(ms_sorted):.0f} ms, "
                f"p50 {ms_sorted[len(ms_sorted) // 2]:.0f} ms, "
                f"max {ms_sorted[-1]:.0f} ms")
        eng.decode_chunk = old_chunk
        (c_wall, c_toks), (s_wall, s_toks) = (results["continuous"],
                                              results["static"])
        log(f"continuous vs static: {c_toks / c_wall:.1f} vs "
            f"{s_toks / s_wall:.1f} tok/s aggregate "
            f"({(c_toks / c_wall) / (s_toks / s_wall):.2f}x)")
        return (c_wall * 1000.0 / max(1, c_toks),
                f"{weights}-continuous{cont}x{B}{cfg_tag}")

    # BENCH_FAULTS=N replays a concurrent workload through the REAL serving
    # scheduler (ServerState + Batcher + supervisor) with a deterministic
    # fault plan installed (DLLAMA_FAULTS, default step_chunk:raise:every=3).
    # The measurement is BOUNDEDNESS, not speed: every request must resolve
    # — tokens or a typed error — within the join timeout, with the
    # supervisor restarting the scheduler through every injected crash. A
    # hang fails the bench. CPU-runnable (BENCH_MODEL=smoke).
    nfaults = _env_count("BENCH_FAULTS")
    if nfaults:
        import threading as _threading

        from dllama_tpu import faults as _faults
        from dllama_tpu.serving.api_server import ServerState

        class _FakeTok:
            # stop handling off: rows run to budget (no tokenizer needed —
            # the replay exercises the scheduler, not detokenization)
            eos_id = -1

            def piece_id(self, _b):
                return -1

        fspec = os.environ.get("DLLAMA_FAULTS") or "step_chunk:raise:every=3"
        plan = _faults.install(fspec)
        st = ServerState(eng, _FakeTok(), cfg, model_name="bench",
                         batch_window_ms=5.0, batch_max=min(4, nfaults),
                         batch_chunk=4)
        rng_f = __import__("numpy").random.default_rng(3)
        fprompt = [int(t) for t in rng_f.integers(1, cfg.vocab_size, 6)]
        fsteps = max(8, bench_steps // 8)
        outcomes = {"ok": 0, "error": 0, "hang": 0}
        olock = _threading.Lock()

        def _one_request():
            try:
                st.batcher.submit(list(fprompt), fsteps,
                                  SamplerConfig(temperature=0.0, seed=0))
                key = "ok"
            except RuntimeError:
                key = "error"  # typed + bounded: exactly the contract
            with olock:
                outcomes[key] += 1

        log(f"fault replay: {nfaults} requests under '{fspec}'")
        t0 = time.perf_counter()
        threads = [_threading.Thread(target=_one_request, daemon=True)
                   for _ in range(nfaults)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
            if t.is_alive():
                with olock:
                    outcomes["hang"] += 1
        wall = time.perf_counter() - t0
        _faults.clear()
        log(f"fault replay: {outcomes} in {wall:.2f}s | injected "
            f"{plan.counters()} | scheduler crashes "
            f"{st.batcher.crash_count}")
        if outcomes["hang"]:
            raise RuntimeError(
                f"fault replay left requests hanging: {outcomes}")
        return (wall * 1000.0 / max(1, nfaults),
                f"{weights}-faults{nfaults}{cfg_tag}")

    # BENCH_INTEGRITY=1 measures the numeric-health watchdog two ways.
    # (1) Overhead: batched decode with checks on vs a second engine built
    #     with numeric_checks=False — the per-row isfinite AND rides the
    #     fused decode scan, so the target is < 1% (CPU numbers are noisy;
    #     the number is REPORTED, the bench does not fail on it).
    # (2) Quarantine replay: a slot-pool run is repeated with
    #     ``logits:nan:row=1`` installed — the poisoned row must finish
    #     "error" and every sibling row's stream must be BIT-IDENTICAL to
    #     the clean run (per-row sampler chains + per-row cache slabs mean
    #     corruption must never cross rows). A divergence fails the bench.
    if _env_count("BENCH_INTEGRITY"):
        from dllama_tpu import faults as _faults

        B = max(2, min(batch or 4, 8))
        isteps = max(16, min(bench_steps, cfg.seq_len - 8) // 2)
        greedy = SamplerConfig(temperature=0.0, seed=0)

        def _timed_batch(e):
            e.generate_batch([[1]] * B, steps=isteps, sampler=greedy)
            best = None
            for _ in range(3):
                t1 = time.perf_counter()
                out = e.generate_batch([[1]] * B, steps=isteps,
                                       sampler=greedy)
                eff = ((time.perf_counter() - t1) * 1000.0
                       / max(1, len(out[0])) / B)
                best = eff if best is None else min(best, eff)
            return best

        log(f"integrity: timing watchdog overhead (B={B}, {isteps} steps)")
        on_ms = _timed_batch(eng)
        # second engine without the watchdog: rebuild params (the first
        # Engine may have fused this frame's reference away)
        if weights in ("q40", "q80"):
            params2 = llama.device_random_quant_params(cfg, kind=weights,
                                                       seed=0)
        else:
            params2 = llama.device_random_params(cfg, seed=0, mesh=mesh)
        eng_off = Engine(cfg, params2, SamplerConfig(temperature=0.0),
                         cache_dtype=cache_dtype, mesh=mesh,
                         decode_chunk=bench_steps, numeric_checks=False)
        del params2
        off_ms = _timed_batch(eng_off)
        overhead = (on_ms - off_ms) / off_ms * 100.0
        log(f"watchdog overhead: on {on_ms:.4f} vs off {off_ms:.4f} "
            f"ms/token effective = {overhead:+.2f}% (target < 1%)")

        def _pool_run(e, fault_spec=None):
            """Admit B sampled rows, drain, return (streams, finishes)."""
            if fault_spec:
                _faults.install(fault_spec)
            try:
                sess = e.batch_session(B, chunk=8)
                slots = [sess.admit([1], steps=isteps,
                                    sampler=SamplerConfig(temperature=0.8,
                                                          seed=100 + i))
                         for i in range(B)]
                streams = {b: [] for b in slots}
                fins = {}
                while len(fins) < B:
                    for b, burst in sess.step_chunk().items():
                        streams[b].extend(burst)
                        if sess.is_done(b) and b not in fins:
                            fins[b] = sess.finish_reason(b)
                            sess.release(b)
                sess.close()
            finally:
                if fault_spec:
                    _faults.clear()
            return ([streams[b] for b in slots], [fins[b] for b in slots])

        log("integrity: quarantine replay (clean, then logits:nan:row=1)")
        clean_streams, clean_fins = _pool_run(eng)
        pois_streams, pois_fins = _pool_run(eng, "logits:nan:row=1")
        if pois_fins[1] != "error":
            raise RuntimeError(
                f"poisoned row finished {pois_fins[1]!r}, want 'error' "
                f"(finishes: {pois_fins})")
        diverged = [i for i in range(B)
                    if i != 1 and pois_streams[i] != clean_streams[i]]
        if diverged:
            raise RuntimeError(
                f"sibling rows {diverged} diverged from the clean run "
                "under a row-1 poisoning — quarantine is not row-isolated")
        log(f"quarantine replay: row 1 finished 'error' after "
            f"{len(pois_streams[1])} tokens; {B - 1} sibling rows "
            f"bit-identical (finishes: {pois_fins})")
        return (on_ms,
                f"{weights}-integrity-b{B}-overhead"
                f"{overhead:.2f}pct{cfg_tag}")

    # BENCH_OBS=N measures the observability subsystem two ways.
    # (1) Overhead: batched decode on the instrumented engine vs a second
    #     engine built with metrics=None — every telemetry point on the hot
    #     path is one `is not None` check plus a histogram observe per
    #     CHUNK (not per token), so the budget is < 1% and the bench FAILS
    #     above it (min-of-reps on identical work keeps CPU noise out).
    # (2) Latency telemetry: N requests replayed through the REAL serving
    #     scheduler on a FRESH registry, once per decode path (solo
    #     sequential, spec all-greedy window, continuous sampled window),
    #     reporting TTFT/TPOT p50/p95 per path from the histogram
    #     reservoirs — the numbers RESULTS.md quotes. CPU-runnable
    #     (BENCH_MODEL=smoke).
    nobs = _env_count("BENCH_OBS")
    if nobs:
        import threading as _threading

        from dllama_tpu import observability as _obs
        from dllama_tpu.serving.api_server import ServerState

        B = max(2, min(batch or 4, 8))
        osteps = max(16, min(bench_steps, cfg.seq_len - 8) // 2)
        greedy = SamplerConfig(temperature=0.0, seed=0)

        def _timed_obs(e):
            e.generate_batch([[1]] * B, steps=osteps, sampler=greedy)
            best = None
            for _ in range(8):
                t1 = time.perf_counter()
                out = e.generate_batch([[1]] * B, steps=osteps,
                                       sampler=greedy)
                eff = ((time.perf_counter() - t1) * 1000.0
                       / max(1, len(out[0])) / B)
                best = eff if best is None else min(best, eff)
            return best

        log(f"obs: timing telemetry overhead (B={B}, {osteps} steps)")
        # the on-leg carries the FULL observability stack: the history
        # sampler + burn-rate engine run at 4x production cadence (0.25s
        # vs the 1s default) against the engine's registry while it
        # decodes, so the <1% budget now covers the sampler thread too.
        # (One full-registry pass costs ~0.8ms of GIL; 20Hz would burn
        # 1.6% on the sampler alone — more than the whole budget.)
        from dllama_tpu.obsv import (BurnRateEngine as _BurnEng,
                                     Sampler as _TsSampler,
                                     TimeSeriesStore as _TsStore)
        from dllama_tpu.serving.lifecycle import parse_slo_classes as _pslo

        _tstore = _TsStore()
        _tsampler = _TsSampler(
            _obs.default_registry(), _tstore, interval_s=0.25,
            hooks=(_BurnEng(_tstore,
                            _pslo("interactive:ttft=500,tpot=50,err=0.01"),
                            _obs.default_registry()).evaluate,))
        if weights in ("q40", "q80"):
            params2 = llama.device_random_quant_params(cfg, kind=weights,
                                                       seed=0)
        else:
            params2 = llama.device_random_params(cfg, seed=0, mesh=mesh)
        eng_off = Engine(cfg, params2, SamplerConfig(temperature=0.0),
                         cache_dtype=cache_dtype, mesh=mesh,
                         decode_chunk=bench_steps, metrics=None)
        del params2
        # paired trials, median delta — a fixed on-first ordering folds
        # ambient machine noise into one side of a sub-percent
        # comparison, and any single trial can catch a burst; a genuine
        # per-token cost shifts every trial. The sampler thread only runs
        # while the instrumented engine is the one being timed.
        deltas, pairs = [], []
        for _ in range(5):
            off_t = _timed_obs(eng_off)
            _tsampler.start()
            try:
                on_t = _timed_obs(eng)
            finally:
                _tsampler.stop()
            pairs.append((on_t, off_t))
            deltas.append((on_t - off_t) / off_t * 100.0)
        overhead = sorted(deltas)[len(deltas) // 2]
        on_ms, off_ms = pairs[sorted(range(len(deltas)),
                                     key=lambda i: deltas[i])[
                                         len(deltas) // 2]]
        log(f"telemetry overhead: on {on_ms:.4f} vs off {off_ms:.4f} "
            f"ms/token effective, median of 5 trials = {overhead:+.2f}% "
            "(budget < 1%; trials "
            + " ".join(f"{d:+.2f}%" for d in deltas) + ")")
        if overhead >= 1.0:
            raise RuntimeError(
                f"telemetry overhead {overhead:+.2f}% exceeds the 1% "
                "budget (instrumented vs metrics=None engine)")

        class _ObsTok:
            eos_id = -1  # no stops: rows run to budget (scheduler replay)

            def piece_id(self, _b):
                return -1

        reg = _obs.MetricsRegistry()  # fresh: percentiles from THIS replay
        st = ServerState(eng, _ObsTok(), cfg, model_name="bench",
                         spec_draft=4, batch_window_ms=5.0, batch_max=B,
                         batch_chunk=8, metrics=reg)
        rng_o = __import__("numpy").random.default_rng(5)
        oprompt = [int(t) for t in rng_o.integers(1, cfg.vocab_size, 6)]
        rsteps = max(8, min(bench_steps // 4, cfg.seq_len - len(oprompt)))

        def _one(i, sampler):
            tr = _obs.RequestTrace(_obs.new_request_id())
            tr.tokens_in = len(oprompt)
            try:
                row = st.batcher.submit(list(oprompt), rsteps, sampler,
                                        trace=tr)
                tr.tokens_out = len(row)
                tr.finish_reason = "length"
            except RuntimeError as e:
                tr.finish_reason = "error"
                log(f"obs replay request failed: {e!r}")
            st.finish_request(tr)

        # solo: sequential singletons; spec: concurrent all-greedy window
        # (spec_draft=4 routes it to the batched verify); continuous:
        # concurrent sampled window (mixed samplers can't speculate)
        plans = [
            ("solo", False, lambda i: greedy),
            ("spec", True, lambda i: greedy),
            ("continuous", True,
             lambda i: SamplerConfig(temperature=0.8, seed=100 + i)),
        ]
        for pname, concurrent, mk in plans:
            log(f"obs replay: {nobs} requests -> {pname} path")
            if concurrent:
                ths = [_threading.Thread(target=_one, args=(i, mk(i)),
                                         daemon=True)
                       for i in range(nobs)]
                for t in ths:
                    t.start()
                for t in ths:
                    t.join(timeout=300.0)
            else:
                for i in range(nobs):
                    _one(i, mk(i))
        for pname in ("solo", "spec", "continuous"):
            n = st._m_ttft.count(path=pname)
            if not n:
                log(f"{pname:>10}: no requests routed here (window "
                    "timing); see dllama_requests_path_total")
                continue
            log(f"{pname:>10}: n={n} TTFT p50 "
                f"{st._m_ttft.percentile(50, path=pname):.1f} ms, p95 "
                f"{st._m_ttft.percentile(95, path=pname):.1f} ms | TPOT "
                f"p50 {st._m_tpot.percentile(50, path=pname):.2f} ms, p95 "
                f"{st._m_tpot.percentile(95, path=pname):.2f} ms")
        routed = {c["labels"].get("path"): c["value"]
                  for c in reg.snapshot()
                  .get("dllama_requests_path_total", {}).get("values", [])}
        log(f"paths routed: {routed}")

        # (3) Fleet front-door A/B: the same proxy hot path through a REAL
        #     RouterState twice — fleet observability on (flight recorder +
        #     a federation scrape loop hitting /metrics/fleet while traffic
        #     flows) vs off — against in-process stub replicas, so the
        #     delta isolates the router-side cost of parent-span headers,
        #     Server-Timing hop attribution, the flight ring, and
        #     concurrent federation. Same < 1% hard-fail budget; stubs are
        #     stdlib HTTP, no jax: CPU-smokeable.
        import http.client as _hc
        import json as _jsn
        from http.server import BaseHTTPRequestHandler as _BH
        from http.server import ThreadingHTTPServer as _TS

        from dllama_tpu.serving import router as _rt

        class _StubReplica(_BH):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *a):
                pass

            def _send(self, body, ctype="application/json", extra=()):
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/ready":
                    self._send(_jsn.dumps({
                        "status": "ok", "replica_id": "bench-stub",
                        "time_us": _obs.mono_to_us(),
                        "load": {"slots_occupied": 0, "slots_total": 8,
                                 "queue_depth": 0, "kv_pages_free": 64,
                                 "kv_pages_total": 64,
                                 "prefix_hit_rate": 0.0}}).encode())
                else:  # /metrics for the federation scrape loop
                    self._send(
                        b"# TYPE dllama_http_requests_total counter\n"
                        b'dllama_http_requests_total{route="/x"} 1\n',
                        ctype="text/plain; version=0.0.4")

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                self._send(
                    _jsn.dumps({"choices": [{"message": {
                        "role": "assistant", "content": "ok"}}]}).encode(),
                    extra=(("Server-Timing",
                            "queue;dur=0.1, prefill;dur=0.2, "
                            "decode;dur=0.3"),))

        def _fleet_up(obs_on):
            """One router fleet (2 stub replicas) with observability on or
            off; returns (router_port, teardown). The on fleet carries the
            full stack — flight recorder, 0.05s history sampler, and a
            hostile federation loop (/metrics/fleet at 20Hz, history +
            alerts at 2Hz, 10-30x denser than any real dashboard)."""
            ups = [_TS(("127.0.0.1", 0), _StubReplica) for _ in range(2)]
            for u in ups:
                _threading.Thread(target=u.serve_forever,
                                  daemon=True).start()
            state = _rt.RouterState(
                [_rt.Replica("127.0.0.1", u.server_address[1])
                 for u in ups],
                probe_interval_s=3600.0, metrics=_obs.MetricsRegistry(),
                enable_flight=obs_on,
                # 0 = the sampler thread never starts on the off fleet
                ts_interval=0.05 if obs_on else 0.0)
            state.probe_once()
            state.sampler.start()
            srv = _rt.create_router_server(state, host="127.0.0.1", port=0)
            port = srv.server_address[1]
            _threading.Thread(target=srv.serve_forever, daemon=True).start()
            stop = _threading.Event()
            if obs_on:
                def _scrape_loop():
                    i = 0
                    while not stop.is_set():
                        state.federate()
                        if i % 10 == 0:
                            state.federate_history(60.0)
                            state.federate_alerts()
                        i += 1
                        stop.wait(0.05)
                _threading.Thread(target=_scrape_loop, daemon=True).start()

            def _down():
                stop.set()
                state.sampler.stop()
                srv.shutdown()
                srv.server_close()
                for u in ups:
                    u.shutdown()
                    u.server_close()
            return port, _down

        log("obs: fleet front-door A/B (proxy hot path, fleet obs on/off)")
        # Both fleets serve SIMULTANEOUSLY and the probe alternates single
        # requests between them (swapping within-pair order every
        # iteration), so both sides sample identical machine conditions —
        # sequential legs fold ambient noise into whichever side runs in
        # the worse window (measured at +-10% phantom deltas on this very
        # comparison). Per trial the p10 per-request floor beats a min
        # (a min is a rare-event statistic); the gate takes the median of
        # three trial deltas — a genuine per-request cost shifts every
        # trial, a burst shifts one.
        body = _jsn.dumps({
            "model": "bench", "max_tokens": 1,
            "messages": [{"role": "user", "content": "x"}]}).encode()
        port_off, down_off = _fleet_up(False)
        port_on, down_on = _fleet_up(True)
        try:
            conn_off = _hc.HTTPConnection("127.0.0.1", port_off)
            conn_on = _hc.HTTPConnection("127.0.0.1", port_on)

            def _one(conn):
                t1 = time.perf_counter()
                conn.request("POST", "/v1/chat/completions", body=body,
                             headers={"Content-Type": "application/json"})
                r = conn.getresponse()
                r.read()
                return (time.perf_counter() - t1) * 1000.0

            for _ in range(100):  # warm sockets, code paths, scrape loop
                _one(conn_off), _one(conn_on)
            deltas, floors = [], []
            for _trial in range(3):
                offs, ons = [], []
                for i in range(400):
                    if i % 2:
                        offs.append(_one(conn_off))
                        ons.append(_one(conn_on))
                    else:
                        ons.append(_one(conn_on))
                        offs.append(_one(conn_off))
                offs.sort()
                ons.sort()
                p_off, p_on = offs[len(offs) // 10], ons[len(ons) // 10]
                floors.append((p_on, p_off))
                deltas.append((p_on - p_off) / p_off * 100.0)
            conn_off.close()
            conn_on.close()
        finally:
            down_on()
            down_off()
        fl_over = sorted(deltas)[len(deltas) // 2]
        fl_on, fl_off = floors[sorted(range(3),
                                      key=lambda i: deltas[i])[1]]
        log(f"fleet front-door overhead: on {fl_on:.3f} vs off "
            f"{fl_off:.3f} ms/request p10, median of 3 trials = "
            f"{fl_over:+.2f}% (budget < 1%; trials "
            + " ".join(f"{d:+.2f}%" for d in deltas) + ")")
        if fl_over >= 1.0:
            raise RuntimeError(
                f"fleet observability overhead {fl_over:+.2f}% exceeds "
                "the 1% budget (flight+sampler+federation on vs off "
                "through the router front door)")
        return (on_ms,
                f"{weights}-obs-b{B}-overhead{overhead:.2f}pct{cfg_tag}")

    # BENCH_SPEC=K measures speculative decoding (prompt-lookup drafts of up
    # to K tokens, exact greedy): solo generate_spec, or — with BENCH_BATCH —
    # generate_batch_spec (draft_len+1 positions x B rows per weight pass).
    # The prompt repeats a short phrase so drafting has something to match;
    # the acceptance rate is printed so the number can be read honestly
    # (random weights don't generate Shakespeare, but greedy loops repeat).
    spec = _env_count("BENCH_SPEC")
    if spec and batch > 1 and not getattr(eng, "supports_batch_spec", True):
        # dense-pjit mesh engines have no shard_map verify wrapper — the
        # spec-batch combination would raise; measure plain batched decode
        # and SAY so instead of dying mid-battery (ADVICE r05)
        log(f"BENCH_SPEC={spec} with BENCH_BATCH={batch}: batched spec "
            "verify unavailable on the dense-pjit mesh path; falling back "
            "to plain batched decode")
        spec = 0
    if spec:
        rng_p = __import__("numpy").random.default_rng(1)
        phrase = [int(t) for t in rng_p.integers(1, cfg.vocab_size, 6)]
        prompt = (phrase * 6)[:30]
        if batch > 1:
            prompts = [list(prompt)] * batch
            log(f"warmup (batched spec, B={batch}, draft={spec})...")
            eng.generate_batch_spec(prompts, steps=bench_steps, draft_len=spec)
            times = []
            for rep in range(3):
                t1 = time.perf_counter()
                rows, stats = eng.generate_batch_spec(
                    prompts, steps=bench_steps, draft_len=spec)
                wall = (time.perf_counter() - t1) * 1000.0
                emitted = stats["emitted"]
                times.append(wall / emitted)
                log(f"rep {rep}: {wall / emitted:.3f} ms/token effective "
                    f"({emitted} tokens, {stats['verify_steps']} launches, "
                    f"{stats['accepted_drafts']} drafts accepted)")
            return min(times), f"{weights}-spec{spec}-batch{batch}{cfg_tag}"
        log(f"warmup (solo spec, draft={spec})...")
        list(eng.generate_spec(list(prompt), steps=bench_steps))
        times = []
        for rep in range(3):
            t1 = time.perf_counter()
            toks = [t for t, _ in eng.generate_spec(list(prompt),
                                                    steps=bench_steps)]
            wall = (time.perf_counter() - t1) * 1000.0
            times.append(wall / max(1, len(toks)))
            log(f"rep {rep}: {wall / max(1, len(toks)):.3f} ms/token "
                f"({len(toks)} tokens)")
        return min(times), f"{weights}-spec{spec}{cfg_tag}{flash_tag}"

    # BENCH_BATCH=N measures BATCHED decode: N sequences share one weight
    # stream per step (Engine.generate_batch), so the reported value is the
    # EFFECTIVE ms/token across the batch (wall / emitted / N) — decode is
    # bandwidth-bound, so this is the throughput headline the reference's
    # batch=1 design cannot post
    if batch > 1:
        log(f"warmup (batch={batch}, {bench_steps} fused steps, incl. compile)...")
        t0 = time.perf_counter()
        eng.generate_batch([[1]] * batch, steps=bench_steps)
        log(f"warmup done in {time.perf_counter() - t0:.1f}s")
        times = []
        for rep in range(3):
            t1 = time.perf_counter()
            out = eng.generate_batch([[1]] * batch, steps=bench_steps)
            wall_ms = (time.perf_counter() - t1) * 1000.0
            emitted = len(out[0])  # generate_batch clamps to the context
            eff = wall_ms / emitted / batch
            times.append(eff)
            log(f"rep {rep}: {wall_ms / emitted:.3f} ms/step over {emitted} "
                f"steps, {eff:.3f} ms/token effective x{batch}")
        return min(times), f"{weights}-batch{batch}{cfg_tag}{flash_tag}"

    log(f"warmup ({bench_steps} fused steps, incl. compile)...")
    t0 = time.perf_counter()
    eng.generate_fused([1], steps=bench_steps)  # same n_steps as the timed runs
    log(f"warmup done in {time.perf_counter() - t0:.1f}s")

    times = []
    for rep in range(3):
        t1 = time.perf_counter()
        toks, _, decode_ms = eng.generate_fused([1], steps=bench_steps)
        wall_ms = (time.perf_counter() - t1) * 1000.0
        times.append(wall_ms / bench_steps)
        log(f"rep {rep}: {wall_ms / bench_steps:.3f} ms/token ({bench_steps} tokens)")
    return min(times), f"{weights}{cfg_tag}{flash_tag}"


def _backend_alive(timeout_s: int = 180) -> tuple:
    """(alive, failure_detail) for the default backend, probed in a
    subprocess — the driver's bench run must record a clean failure instead
    of hanging its whole wall-clock budget on a dead tunnel."""
    return _run_probe("import jax; jax.devices(); print('BK_OK')",
                      "BK_OK", timeout_s)


def run_router_bench(n: int) -> dict:
    """BENCH_ROUTER=N: fleet front-door replay, jax-free IN THIS PROCESS
    (the replicas are `cli serve` subprocesses pinned to CPU). Four phases
    against a 2-replica fleet of the smoke shape:

      solo      N staggered chat requests through a router over ONE replica
      fleet     the same workload through a router over both — aggregate
                req/s must beat solo (gate enforced only on multi-core
                hosts: a 1-CPU runner timeshares the replicas, recorded as
                gate_fleet_enforced=false)
      affinity  two-turn conversations: warm-turn TTFT under prefix
                affinity (second turn lands where the radix-cache pages
                are hot) vs the EXPECTED VALUE of uniform-random routing
                over 2 replicas (half the warm turns deliberately land on
                the cold replica) — affinity p50 must win; the baseline
                even skips the router hop, so the comparison is
                conservative
      failover  SIGKILL one replica mid-replay: every request must
                resolve. Requests already in flight on the dead replica
                may error (reported as inflight_errors; buffered responses
                actually re-dispatch, so usually zero) but anything
                started AFTER the kill must come back 200 via the
                surviving replica. Zero dropped non-inflight requests.

    BENCH_ROUTER_OUT writes the full report JSON for CI artifacts. The
    final metric line is fleet req/s with vs_baseline = fleet/solo."""
    import http.client
    import shutil
    import socket
    import tempfile
    import threading

    import numpy as np

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.tokenizer_file import (TokenizerData,
                                                   write_tokenizer)
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.quants import blocks
    from dllama_tpu.serving import fleet as fleet_mod
    from dllama_tpu.serving import router as router_mod

    n_req = max(6, min(n, 32))
    k_conv = 8
    tmp = tempfile.mkdtemp(prefix="bench_router_")
    # a deeper/longer-context cousin of the BENCH_PREFIX smoke shape: the
    # affinity phase needs a ~700-token shared prefix whose prefill COST
    # dominates the router hop (+~0.5 ms), or warm-vs-cold TTFT drowns in
    # HTTP noise — yet small enough that a 2-CPU-replica fleet fits CI
    spec = ModelSpec(arch=ArchType.LLAMA, dim=256, hidden_dim=512,
                     n_layers=6, n_heads=8, n_kv_heads=4, vocab_size=512,
                     seq_len=1024, weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    model, tok = os.path.join(tmp, "m.m"), os.path.join(tmp, "t.t")
    write_model(model, spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(
                    np.float32) for e in tensor_plan(spec)})
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
             + [b"hi"] * (512 - 259))
    write_tokenizer(tok, TokenizerData(vocab=vocab, scores=[0.0] * 512,
                                       bos_id=1, eos_id=2))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_PLATFORM_NAME", None)
    # CPU children must not register the axon TPU plugin (single-session
    # tunnel: a second registrant blocks at interpreter start)
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def _free_base(span: int) -> int:
        """A base port with `span` consecutive free ports above it."""
        for _ in range(64):
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                base = s.getsockname()[1]
            if base + span > 65500:
                continue
            try:
                for i in range(1, span):
                    with socket.socket() as t:
                        t.bind(("127.0.0.1", base + i))
                return base
            except OSError:
                continue
        raise RuntimeError("no free port span for the replica fleet")

    fl = fleet_mod.Fleet(
        model, tok, n_replicas=2, base_port=_free_base(2), host="127.0.0.1",
        # --tp 1: CI lanes force 8 virtual CPU devices via XLA_FLAGS and
        # the smoke shape's 4 kv heads can't shard 8 ways; --kv-pages
        # turns on the radix prefix cache the affinity phase measures; the
        # 40 ms window makes request+companion pairing reliable (the
        # scheduler routes singleton windows to the solo path, which
        # bypasses the paged radix cache)
        # --batch-chunk 2: content bursts every 2 decode steps, so TTFT
        # reflects PREFILL (what affinity saves) instead of a full fused
        # chunk; --prefill-chunk 256 keeps the cold ~800-token prefill a
        # handful of scheduler ticks and the warm aliased tail a single one
        replica_args=["--batch-window", "40", "--batch-max", "4",
                      "--batch-chunk", "2", "--prefill-chunk", "256",
                      "--kv-pages", "16", "--tp", "1"],
        log_dir=os.path.join(tmp, "logs"), env=env)
    rep_ports = [r.port for r in fl.replicas]
    routers = []  # (state, server) for teardown

    def _mk_router(reps):
        st = router_mod.RouterState(
            [router_mod.Replica("127.0.0.1", p) for p in reps],
            probe_interval_s=0.5, affinity_block=64)
        st.probe_once()
        srv = router_mod.create_router_server(st, "127.0.0.1", 0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        st.start_probes()
        routers.append((st, srv))
        return srv.server_address[1]

    def _msgs(i, tag, turns=1):
        # ~700-char system prompt (byte-fallback tokenizer: ~1 token/char):
        # covers the 64-byte affinity block and ~44 replica KV pages, so a
        # warm second turn skips a prefill the stopwatch can actually see
        sys_p = (f"[{tag}-{i}] You are a terse operations assistant. "
                 + "Answer in one word. Never apologize, never elaborate, "
                   "never repeat the question back to the user. " * 6)
        msgs = [{"role": "system", "content": sys_p},
                {"role": "user", "content": f"first question for {tag}{i}"}]
        if turns > 1:
            msgs += [{"role": "assistant", "content": "ok"},
                     {"role": "user",
                      "content": f"second question for {tag}{i}"}]
        return msgs

    def _chat(port, messages, stream=False, timeout=120.0):
        """-> (status, total_ms, ttft_ms-or-None). TTFT = first CONTENT
        delta arriving at this client — the server emits its role-preamble
        chunk at admission, BEFORE prefill, so `data:` alone lands ~2 ms
        after connect regardless of prompt length."""
        body = json.dumps({"model": "bench", "messages": messages,
                           "max_tokens": 8, "temperature": 0.0,
                           "stream": stream}).encode()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        t0 = time.perf_counter()
        try:
            conn.request("POST", "/v1/chat/completions", body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            ttft = None
            if stream and resp.status == 200:
                buf = b""
                while b'"content"' not in buf:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    buf += chunk
                ttft = (time.perf_counter() - t0) * 1000.0
            resp.read()
            return resp.status, (time.perf_counter() - t0) * 1000.0, ttft
        finally:
            conn.close()

    def _replay(port, tag, count, stagger_s=0.05):
        """Staggered-arrival replay -> (req/s, n_ok)."""
        results = [None] * count

        def _one(i):
            try:
                status, ms, _ = _chat(port, _msgs(i, tag))
                results[i] = status
            except Exception:  # noqa: BLE001 — a reset mid-response counts as a drop
                results[i] = -1
        threads = []
        t0 = time.perf_counter()
        for i in range(count):
            th = threading.Thread(target=_one, args=(i,), daemon=True)
            th.start()
            threads.append(th)
            time.sleep(stagger_s)
        for th in threads:
            th.join(timeout=240.0)
        wall = time.perf_counter() - t0
        return count / wall, sum(1 for r in results if r == 200)

    gates = []
    try:
        log(f"router bench: booting 2-replica CPU fleet "
            f"(ports {rep_ports})...")
        t0 = time.perf_counter()
        fl.start()
        if not fl.wait_ready(timeout_s=300.0):
            raise RuntimeError("fleet replicas never became ready")
        log(f"fleet ready in {time.perf_counter() - t0:.1f}s")
        solo_port = _mk_router(rep_ports[:1])
        fleet_port = _mk_router(rep_ports)

        # -- throughput: solo vs fleet under the SAME staggered arrivals
        rps_solo, ok_solo = _replay(solo_port, "solo", n_req)
        log(f"solo: {rps_solo:.2f} req/s ({ok_solo}/{n_req} ok)")
        rps_fleet, ok_fleet = _replay(fleet_port, "fleet", n_req)
        log(f"fleet-of-2: {rps_fleet:.2f} req/s ({ok_fleet}/{n_req} ok)")
        gate_fleet = (os.cpu_count() or 1) >= 2
        if ok_solo != n_req or ok_fleet != n_req:
            gates.append(f"throughput replay dropped requests: "
                         f"solo {ok_solo}/{n_req}, fleet {ok_fleet}/{n_req}")
        if gate_fleet and rps_fleet <= rps_solo:
            gates.append(f"fleet {rps_fleet:.2f} req/s did not beat solo "
                         f"{rps_solo:.2f} on a {os.cpu_count()}-core host")

        # -- affinity: warm-turn TTFT, routed vs expected-uniform-random.
        # Every measured request ships with a concurrent cheap companion:
        # a singleton admission window takes the solo path, which bypasses
        # the paged radix cache entirely — only a window of >=2 rows runs
        # the continuous (paged) path where the seed's prompt pages get
        # published and the warm turn aliases them. Seed and warm run back
        # to back per conversation so LRU pressure can't evict the pages
        # in between.
        co_seq = [0]

        def _with_companion(port, msgs, stream=False, co_ports=None):
            # co_ports: where the companions go. A routed request's landing
            # replica is the router's choice, so router-phase callers pass
            # BOTH replica ports — the one the request hits gets a window
            # partner, the other digests a lone ping on the solo path
            dones = []
            for cp in (co_ports or [port]):
                co_seq[0] += 1
                done = threading.Event()
                dones.append(done)

                def _co(seq, cport, ev):
                    try:
                        _chat(cport, [{"role": "user",
                                       "content": f"companion ping {seq}"}])
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                    finally:
                        ev.set()
                threading.Thread(target=_co, args=(co_seq[0], cp, done),
                                 daemon=True).start()
            out = _chat(port, msgs, stream=stream)
            for done in dones:
                done.wait(timeout=120.0)
            return out

        # compile warm-up: the first long-prompt prefill piece and the
        # batch>=2 decode groups each compile once per replica — pay that
        # on a throwaway conversation so neither measured phase eats it
        for p in rep_ports:
            _with_companion(p, _msgs(99, "wup"))
            _with_companion(p, _msgs(99, "wup", turns=2), stream=True)

        aff_ttfts, uni_ttfts = [], []
        for i in range(k_conv):
            st, _, _ = _with_companion(fleet_port, _msgs(i, "aff"),
                                       co_ports=rep_ports)
            if st != 200:
                raise RuntimeError(f"affinity seed {i} got {st}")
            st, _, ttft = _with_companion(
                fleet_port, _msgs(i, "aff", turns=2), stream=True,
                co_ports=rep_ports)
            if st != 200 or ttft is None:
                raise RuntimeError(f"affinity warm turn {i} got {st}")
            aff_ttfts.append(ttft)
        for i in range(k_conv):
            # co_ports=rep_ports here too: BOTH phases pay the same lone
            # companion on the other replica, so the 1-CPU host's
            # timesharing penalty cancels out of the comparison
            st, _, _ = _with_companion(rep_ports[i % 2], _msgs(i, "uni"),
                                       co_ports=rep_ports)
            if st != 200:
                raise RuntimeError(f"uniform seed {i} got {st}")
            # half hit the seeded replica, half the other one: the
            # deterministic expected value of coin-flip routing
            hit = i < k_conv // 2
            port_i = rep_ports[i % 2 if hit else (i + 1) % 2]
            st, _, ttft = _with_companion(
                port_i, _msgs(i, "uni", turns=2), stream=True,
                co_ports=rep_ports)
            if st != 200 or ttft is None:
                raise RuntimeError(f"uniform warm turn {i} got {st}")
            uni_ttfts.append(ttft)
        # diagnostic, not a gate: a nonzero replica hit rate proves the
        # radix cache (not scheduling noise) produced the TTFT split
        hit_rates = []
        for p in rep_ports:
            try:
                c = http.client.HTTPConnection("127.0.0.1", p, timeout=5.0)
                c.request("GET", "/ready")
                rd = json.loads(c.getresponse().read())
                c.close()
                hit_rates.append(round(
                    float(rd.get("prefix_hit_rate", 0.0)), 4))
            except (OSError, ValueError):
                hit_rates.append(None)
        aff_p50, uni_p50 = _pct(aff_ttfts, 50), _pct(uni_ttfts, 50)
        log(f"warm-turn TTFT p50: affinity {aff_p50:.1f} ms vs "
            f"uniform-random {uni_p50:.1f} ms "
            f"(replica prefix hit rates {hit_rates})")
        if aff_p50 >= uni_p50:
            gates.append(f"affinity warm TTFT p50 {aff_p50:.1f} ms is not "
                         f"below uniform-random {uni_p50:.1f} ms")

        # -- failover: SIGKILL replica 0 mid-replay
        m = 10
        results, started = [None] * m, [0.0] * m
        kill_marker = [None]
        t0 = time.perf_counter()

        def _one(i):
            started[i] = time.perf_counter() - t0
            try:
                st, _, _ = _chat(fleet_port, _msgs(i, "kill"), timeout=90.0)
                results[i] = st
            except Exception:  # noqa: BLE001 — a reset mid-response counts as an error
                results[i] = -1

        def _kill():
            time.sleep(0.45)
            kill_marker[0] = time.perf_counter() - t0
            fl.replicas[0].proc.kill()
            log(f"killed replica 0 at t+{kill_marker[0]:.2f}s")
        threading.Thread(target=_kill, daemon=True).start()
        threads = []
        for i in range(m):
            th = threading.Thread(target=_one, args=(i,), daemon=True)
            th.start()
            threads.append(th)
            time.sleep(0.15)
        for th in threads:
            th.join(timeout=180.0)
        hung = sum(1 for r in results if r is None)
        kill_t = kill_marker[0] if kill_marker[0] is not None else 0.0
        post_kill_errors = sum(
            1 for i, r in enumerate(results)
            if r != 200 and r is not None and started[i] >= kill_t)
        inflight_errors = sum(
            1 for i, r in enumerate(results)
            if r != 200 and r is not None and started[i] < kill_t)
        n_ok = sum(1 for r in results if r == 200)
        log(f"failover: {n_ok}/{m} ok, {inflight_errors} in-flight errors, "
            f"{post_kill_errors} post-kill errors, {hung} hung")
        if hung:
            gates.append(f"{hung} requests never resolved after the kill")
        if post_kill_errors:
            gates.append(f"{post_kill_errors} requests started after the "
                         "kill failed — failover dropped non-inflight work")
    finally:
        for st, srv in routers:
            st.stop_probes()
            srv.shutdown()
            srv.server_close()
        fl.drain(timeout_s=10.0)
        shutil.rmtree(tmp, ignore_errors=True)

    report = {
        "requests": n_req, "replicas": 2, "cpu_count": os.cpu_count(),
        "solo_req_per_s": round(rps_solo, 3),
        "fleet_req_per_s": round(rps_fleet, 3),
        "fleet_vs_solo": round(rps_fleet / rps_solo, 3),
        "gate_fleet_enforced": gate_fleet,
        "affinity_warm_ttft_p50_ms": round(aff_p50, 3),
        "uniform_warm_ttft_p50_ms": round(uni_p50, 3),
        "affinity_warm_ttft_ms": [round(t, 1) for t in aff_ttfts],
        "uniform_warm_ttft_ms": [round(t, 1) for t in uni_ttfts],
        "replica_prefix_hit_rates": hit_rates,
        "failover": {"total": m, "ok": n_ok,
                     "inflight_errors": inflight_errors,
                     "post_kill_errors": post_kill_errors, "hung": hung},
        "gates_failed": gates,
    }
    out_path = os.environ.get("BENCH_ROUTER_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2)
        log(f"report written to {out_path}")
    result = {
        "metric": "smoke_router_req_per_s",
        "value": round(rps_fleet, 3),
        "unit": "req/s",
        "vs_baseline": round(rps_fleet / rps_solo, 2),
        "baseline": "same workload through a router over ONE replica",
        "weights": "q40-router-fleet2",
        "platform": "cpu-subprocess-fleet",
        "n_devices": 2,
    }
    if gates:
        result["error"] = "; ".join(gates)
    return result


def run_disagg_bench(n: int) -> dict:
    """BENCH_DISAGG=N: disaggregated-serving replay, jax-free IN THIS
    PROCESS (replicas are `cli serve` subprocesses pinned to CPU). The
    SAME staggered streamed workload runs through two 2-replica fleets of
    the router-bench shape, booted back to back:

      colocated   two "both" replicas — every request prefills and
                  decodes on one replica, no migration (the baseline)
      disagg      one dedicated-prefill + one dedicated-decode replica —
                  every request prefills on the prefill replica and
                  migrates its KV pages to the decode replica at first
                  token

    Gates (the bench itself FAILS on any):
      * zero dropped requests in either leg
      * the disagg leg actually migrated EVERY request (the router's
        outcome="ok" counter delta equals the request count — a leg that
        silently fell back to normal routing would "win" the latency
        comparison by not doing the work)
      * migrated TTFB p50 <= colocated TTFB p50 x DISAGG_SLACK + 250 ms
        (slack 1.5 by default: the handoff adds one HTTP hop plus a page
        encode/decode, which must stay a bounded tax on first-token
        latency, not a multiple; the additive grace absorbs CPU-runner
        scheduling noise on what is a sub-second quantity)

    BENCH_DISAGG_OUT writes the full report JSON for CI artifacts. The
    final metric line is migrated TTFB p50 with vs_baseline =
    colocated/migrated (below 1.0 = migration costs latency)."""
    import http.client
    import shutil
    import socket
    import tempfile
    import threading

    import numpy as np

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.tokenizer_file import (TokenizerData,
                                                   write_tokenizer)
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.quants import blocks
    from dllama_tpu.serving import fleet as fleet_mod
    from dllama_tpu.serving import router as router_mod

    n_req = max(4, min(n, 24))
    slack = float(os.environ.get("DISAGG_SLACK", "1.5"))
    tmp = tempfile.mkdtemp(prefix="bench_disagg_")
    # the router-bench shape: a ~700-token prompt whose prefill cost
    # dominates the HTTP hop, so TTFB measures work, not socket latency
    spec = ModelSpec(arch=ArchType.LLAMA, dim=256, hidden_dim=512,
                     n_layers=6, n_heads=8, n_kv_heads=4, vocab_size=512,
                     seq_len=1024, weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    model, tok = os.path.join(tmp, "m.m"), os.path.join(tmp, "t.t")
    write_model(model, spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(
                    np.float32) for e in tensor_plan(spec)})
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
             + [b"hi"] * (512 - 259))
    write_tokenizer(tok, TokenizerData(vocab=vocab, scores=[0.0] * 512,
                                       bos_id=1, eos_id=2))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("DLLAMA_FAULTS", None)

    def _free_base(span: int) -> int:
        for _ in range(64):
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                base = s.getsockname()[1]
            if base + span > 65500:
                continue
            try:
                for i in range(1, span):
                    with socket.socket() as t:
                        t.bind(("127.0.0.1", base + i))
                return base
            except OSError:
                continue
        raise RuntimeError("no free port span for the replica fleet")

    def _msgs(i, tag):
        sys_p = (f"[{tag}-{i}] You are a terse operations assistant. "
                 + "Answer in one word. Never apologize, never elaborate, "
                   "never repeat the question back to the user. " * 6)
        return [{"role": "system", "content": sys_p},
                {"role": "user", "content": f"question for {tag}{i}"}]

    def _chat_ttfb(port, messages, timeout=180.0):
        """-> (status, ttfb_ms-or-None): streamed request, clocking the
        first CONTENT delta (the role preamble lands pre-prefill)."""
        body = json.dumps({"model": "bench", "messages": messages,
                           "max_tokens": 8, "temperature": 0.0,
                           "stream": True}).encode()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        t0 = time.perf_counter()
        try:
            conn.request("POST", "/v1/chat/completions", body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            ttfb = None
            if resp.status == 200:
                buf = b""
                while b'"content"' not in buf:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    buf += chunk
                else:
                    ttfb = (time.perf_counter() - t0) * 1000.0
            resp.read()
            return resp.status, ttfb
        finally:
            conn.close()

    def _leg(tag, roles):
        """Boot a 2-replica fleet with the given roles behind a fresh
        router, replay the workload, tear it all down. Returns
        (ttfbs, n_ok, migrations_by_outcome)."""
        fl = fleet_mod.Fleet(
            model, tok, n_replicas=2, base_port=_free_base(2),
            host="127.0.0.1",
            replica_args=["--batch-window", "40", "--batch-max", "4",
                          "--batch-chunk", "2", "--prefill-chunk", "256",
                          "--kv-pages", "16", "--tp", "1"],
            log_dir=os.path.join(tmp, f"logs-{tag}"), env=env, roles=roles)
        st = None
        srv = None
        try:
            log(f"disagg bench [{tag}]: booting {'+'.join(roles)} fleet "
                f"(ports {[r.port for r in fl.replicas]})...")
            t0 = time.perf_counter()
            fl.start()
            if not fl.wait_ready(timeout_s=300.0):
                raise RuntimeError(f"[{tag}] replicas never became ready")
            log(f"[{tag}] fleet ready in {time.perf_counter() - t0:.1f}s")
            st = router_mod.RouterState(
                [router_mod.Replica("127.0.0.1", r.port)
                 for r in fl.replicas], probe_interval_s=0.5)
            st.probe_once()
            srv = router_mod.create_router_server(st, "127.0.0.1", 0)
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            st.start_probes()
            port = srv.server_address[1]

            def _migrations():
                fam = st.metrics.snapshot().get(
                    "dllama_kv_transfer_migrations_total", {})
                return {v["labels"]["outcome"]: v["value"]
                        for v in fam.get("values", [])}

            # warm-up through the front door: compiles each replica's
            # prefill/decode programs — and, in the disagg leg, the whole
            # export/import path — outside the stopwatch. Two requests so
            # BOTH colocated replicas compile (least-load alternates).
            for w in range(2):
                stt, _ = _chat_ttfb(port, _msgs(w, f"wup-{tag}"))
                if stt != 200:
                    raise RuntimeError(f"[{tag}] warm-up {w} got {stt}")
            base_ok = _migrations().get("ok", 0)

            ttfbs, statuses = [None] * n_req, [None] * n_req

            def _one(i):
                try:
                    statuses[i], ttfbs[i] = _chat_ttfb(
                        port, _msgs(i, tag))
                except Exception:  # noqa: BLE001 — a reset counts as a drop
                    statuses[i] = -1
            threads = []
            for i in range(n_req):
                th = threading.Thread(target=_one, args=(i,), daemon=True)
                th.start()
                threads.append(th)
                time.sleep(0.2)
            for th in threads:
                th.join(timeout=240.0)
            n_ok = sum(1 for s_ in statuses if s_ == 200)
            mig = _migrations()
            mig["ok_delta"] = mig.get("ok", 0) - base_ok
            return [t for t in ttfbs if t is not None], n_ok, mig
        finally:
            if st is not None:
                st.stop_probes()
            if srv is not None:
                srv.shutdown()
                srv.server_close()
            fl.drain(timeout_s=10.0)

    gates = []
    try:
        colo_ttfbs, colo_ok, _ = _leg("colo", ["both", "both"])
        colo_p50 = _pct(colo_ttfbs, 50)
        log(f"colocated: {colo_ok}/{n_req} ok, TTFB p50 {colo_p50:.1f} ms")
        mig_ttfbs, mig_ok, mig = _leg("disagg", ["prefill", "decode"])
        mig_p50 = _pct(mig_ttfbs, 50)
        log(f"disaggregated: {mig_ok}/{n_req} ok, TTFB p50 "
            f"{mig_p50:.1f} ms, migrations {mig}")
        if colo_ok != n_req or mig_ok != n_req:
            gates.append(f"dropped requests: colocated {colo_ok}/{n_req}, "
                         f"disaggregated {mig_ok}/{n_req}")
        if mig["ok_delta"] < n_req:
            gates.append(
                f"only {mig['ok_delta']:.0f}/{n_req} requests migrated "
                f"(outcomes {mig}) — the latency comparison would credit "
                "normal routing, not the handoff")
        bound = colo_p50 * slack + 250.0
        if mig_p50 > bound:
            gates.append(f"migrated TTFB p50 {mig_p50:.1f} ms exceeds "
                         f"colocated {colo_p50:.1f} ms x {slack} + 250 ms "
                         f"= {bound:.1f} ms")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    report = {
        "requests": n_req, "slack": slack, "cpu_count": os.cpu_count(),
        # CPU smoke: scheduling + handoff correctness only. The latency
        # case for disaggregation (prefill interference on decode TPOT,
        # inter-chip page transfer) is a hardware property — numbers owed
        # once the TPU tunnel resolves (ROADMAP carried follow-up).
        "tpu_deltas_owed": True,
        "colocated_ttfb_p50_ms": round(colo_p50, 3),
        "migrated_ttfb_p50_ms": round(mig_p50, 3),
        "colocated_ttfb_ms": [round(t, 1) for t in colo_ttfbs],
        "migrated_ttfb_ms": [round(t, 1) for t in mig_ttfbs],
        "migrations": {k: round(v, 0) for k, v in mig.items()},
        "gates_failed": gates,
    }
    out_path = os.environ.get("BENCH_DISAGG_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2)
        log(f"report written to {out_path}")
    result = {
        "metric": "smoke_disagg_ttfb_ms",
        "value": round(mig_p50, 3),
        "unit": "ms",
        "vs_baseline": round(colo_p50 / mig_p50, 2) if mig_p50 else None,
        "baseline": "same streamed workload on a colocated 2-replica fleet "
                    "(no migration)",
        "weights": "q40-disagg-fleet2",
        "platform": "cpu-subprocess-fleet",
        "n_devices": 2,
    }
    if gates:
        result["error"] = "; ".join(gates)
    return result


def run_failover_bench(n: int) -> dict:
    """BENCH_FAILOVER=N: checkpointing-overhead replay, jax-free IN THIS
    PROCESS (replicas are `cli serve` subprocesses pinned to CPU). ONE
    2-replica "both" fleet boots once; the SAME sequential decode-heavy
    workload then runs through two routers back to back:

      base   router with --ckpt-interval 0 — no checkpoint frames are
             requested, the stream is the plain batched decode path
      ckpt   router with the default --ckpt-interval — every stream
             opts in, replicas serialize + ship a KV checkpoint every
             K emitted tokens

    Both legs measure per-request TPOT (first content delta -> [DONE],
    divided by the tokens decoded after the first burst), so the delta
    is exactly the checkpoint tax: export_row + encode + one extra SSE
    frame per K tokens, amortized.

    Gates (the bench itself FAILS on any):
      * zero dropped requests in either leg
      * the ckpt leg actually checkpointed — the replicas'
        dllama_ckpt_writes_total{outcome="ok"} sum grew by at least one
        per request (a leg that silently skipped checkpointing would
        "win" the overhead comparison by not doing the work)
      * ckpt TPOT p50 <= base TPOT p50 x 1.01 + FAILOVER_TPOT_SLACK_MS
        (default 20 ms: the ISSUE's <1% overhead budget, plus an
        additive grace because a tiny-model CPU TPOT is a handful of
        milliseconds and scheduler noise would otherwise dwarf the
        quantity being gated)

    BENCH_FAILOVER_OUT writes the full report JSON for CI artifacts.
    The final metric line is ckpt-leg TPOT p50 with vs_baseline =
    base/ckpt (below 1.0 = checkpointing costs decode throughput)."""
    import http.client
    import shutil
    import socket
    import tempfile
    import threading

    import numpy as np

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.tokenizer_file import (TokenizerData,
                                                   write_tokenizer)
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.quants import blocks
    from dllama_tpu.serving import fleet as fleet_mod
    from dllama_tpu.serving import router as router_mod

    n_req = max(4, min(n, 16))
    max_tok = 48
    ckpt_k = 32  # the default --ckpt-interval: the cadence the gate is
    #              specified against
    slack_ms = float(os.environ.get("FAILOVER_TPOT_SLACK_MS", "20"))
    tmp = tempfile.mkdtemp(prefix="bench_failover_")
    spec = ModelSpec(arch=ArchType.LLAMA, dim=256, hidden_dim=512,
                     n_layers=6, n_heads=8, n_kv_heads=4, vocab_size=512,
                     seq_len=1024, weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    model, tok = os.path.join(tmp, "m.m"), os.path.join(tmp, "t.t")
    write_model(model, spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(
                    np.float32) for e in tensor_plan(spec)})
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
             + [b"hi"] * (512 - 259))
    write_tokenizer(tok, TokenizerData(vocab=vocab, scores=[0.0] * 512,
                                       bos_id=1, eos_id=2))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("DLLAMA_FAULTS", None)

    def _free_base(span: int) -> int:
        for _ in range(64):
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                base = s.getsockname()[1]
            if base + span > 65500:
                continue
            try:
                for i in range(1, span):
                    with socket.socket() as t:
                        t.bind(("127.0.0.1", base + i))
                return base
            except OSError:
                continue
        raise RuntimeError("no free port span for the replica fleet")

    def _chat_tpot(port, i, tag, timeout=180.0):
        """-> (status, tpot_ms-or-None): streamed request, clocking first
        content delta -> [DONE] over the tokens decoded after the first
        burst (batch-chunk 2, so max_tok - 2 of them)."""
        body = json.dumps({
            "model": "bench",
            "messages": [{"role": "user", "content": f"[{tag}-{i}] go"}],
            "max_tokens": max_tok, "temperature": 0.0,
            "stream": True}).encode()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        try:
            conn.request("POST", "/v1/chat/completions", body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                return resp.status, None
            buf, t_first, t_done = b"", None, None
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    break
                buf += chunk
                if t_first is None and b'"content"' in buf:
                    t_first = time.perf_counter()
                if b"data: [DONE]" in buf:
                    t_done = time.perf_counter()
                    break
            resp.read()
            if t_first is None or t_done is None:
                return -1, None  # torn stream = a drop
            return 200, (t_done - t_first) * 1000.0 / max(1, max_tok - 2)
        finally:
            conn.close()

    def _ckpt_writes(ports):
        total = 0.0
        for p in ports:
            conn = http.client.HTTPConnection("127.0.0.1", p, timeout=10.0)
            try:
                conn.request("GET", "/metrics")
                text = conn.getresponse().read().decode()
            finally:
                conn.close()
            for line in text.splitlines():
                if (line.startswith("dllama_ckpt_writes_total")
                        and 'outcome="ok"' in line):
                    total += float(line.rsplit(" ", 1)[1])
        return total

    gates = []
    fl = fleet_mod.Fleet(
        model, tok, n_replicas=2, base_port=_free_base(2), host="127.0.0.1",
        replica_args=["--batch-window", "40", "--batch-max", "4",
                      "--batch-chunk", "2", "--prefill-chunk", "256",
                      "--kv-pages", "16", "--tp", "1",
                      "--ckpt-interval", str(ckpt_k)],
        log_dir=os.path.join(tmp, "logs"), env=env, roles=["both", "both"])
    legs = {}
    try:
        log("failover bench: booting both+both fleet "
            f"(ports {[r.port for r in fl.replicas]})...")
        t0 = time.perf_counter()
        fl.start()
        if not fl.wait_ready(timeout_s=300.0):
            raise RuntimeError("replicas never became ready")
        log(f"fleet ready in {time.perf_counter() - t0:.1f}s")
        ports = [r.port for r in fl.replicas]

        for tag, interval in (("base", 0), ("ckpt", ckpt_k)):
            st = router_mod.RouterState(
                [router_mod.Replica("127.0.0.1", p) for p in ports],
                probe_interval_s=0.5, ckpt_interval=interval)
            st.probe_once()
            srv = router_mod.create_router_server(st, "127.0.0.1", 0)
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            st.start_probes()
            port = srv.server_address[1]
            try:
                # warm-up: compile both replicas' programs (and, in the
                # ckpt leg, the export path) outside the stopwatch
                for w in range(2):
                    stt, _ = _chat_tpot(port, w, f"wup-{tag}")
                    if stt != 200:
                        raise RuntimeError(f"[{tag}] warm-up {w} got {stt}")
                writes0 = _ckpt_writes(ports)
                tpots, n_ok = [], 0
                for i in range(n_req):  # sequential: TPOT, not throughput
                    stt, tpot = _chat_tpot(port, i, tag)
                    if stt == 200 and tpot is not None:
                        n_ok += 1
                        tpots.append(tpot)
                writes = _ckpt_writes(ports) - writes0
                legs[tag] = {"tpots": tpots, "ok": n_ok, "writes": writes}
                log(f"[{tag}] {n_ok}/{n_req} ok, TPOT p50 "
                    f"{_pct(tpots, 50):.2f} ms/token, "
                    f"ckpt writes {writes:.0f}")
            finally:
                st.stop_probes()
                srv.shutdown()
                srv.server_close()

        base_p50 = _pct(legs["base"]["tpots"], 50)
        ckpt_p50 = _pct(legs["ckpt"]["tpots"], 50)
        if legs["base"]["ok"] != n_req or legs["ckpt"]["ok"] != n_req:
            gates.append(f"dropped requests: base {legs['base']['ok']}"
                         f"/{n_req}, ckpt {legs['ckpt']['ok']}/{n_req}")
        if legs["ckpt"]["writes"] < n_req:
            gates.append(
                f"only {legs['ckpt']['writes']:.0f} checkpoints written "
                f"for {n_req} requests — the overhead comparison would "
                "credit a leg that skipped the work")
        bound = base_p50 * 1.01 + slack_ms
        if ckpt_p50 > bound:
            gates.append(f"ckpt TPOT p50 {ckpt_p50:.2f} ms exceeds base "
                         f"{base_p50:.2f} ms x 1.01 + {slack_ms:.0f} ms "
                         f"= {bound:.2f} ms")
    finally:
        fl.drain(timeout_s=10.0)
        shutil.rmtree(tmp, ignore_errors=True)

    report = {
        "requests": n_req, "max_tokens": max_tok,
        "ckpt_interval": ckpt_k, "tpot_slack_ms": slack_ms,
        "cpu_count": os.cpu_count(),
        # CPU smoke: checkpoint-cadence correctness + a noise-bounded
        # overhead gate. The real <1% TPOT budget is a hardware claim
        # (export_row DMA + codec cost vs TPU decode step) — numbers
        # owed once the TPU tunnel resolves (ROADMAP carried follow-up).
        "tpu_deltas_owed": True,
        "base_tpot_p50_ms": round(base_p50, 3),
        "ckpt_tpot_p50_ms": round(ckpt_p50, 3),
        "base_tpot_ms": [round(t, 2) for t in legs["base"]["tpots"]],
        "ckpt_tpot_ms": [round(t, 2) for t in legs["ckpt"]["tpots"]],
        "ckpt_writes": round(legs["ckpt"]["writes"], 0),
        "gates_failed": gates,
    }
    out_path = os.environ.get("BENCH_FAILOVER_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2)
        log(f"report written to {out_path}")
    result = {
        "metric": "smoke_failover_tpot_ms",
        "value": round(ckpt_p50, 3),
        "unit": "ms/token",
        "vs_baseline": round(base_p50 / ckpt_p50, 2) if ckpt_p50 else None,
        "baseline": "same sequential streamed workload through a router "
                    "with checkpointing disabled (--ckpt-interval 0)",
        "weights": "q40-failover-fleet2",
        "platform": "cpu-subprocess-fleet",
        "n_devices": 2,
    }
    if gates:
        result["error"] = "; ".join(gates)
    return result


def run_workloads_bench(n: int) -> dict:
    """BENCH_WORKLOADS=N: the SLO-class chaos battery, jax-free IN THIS
    PROCESS (replicas are `cli serve` subprocesses pinned to CPU). One
    2-replica fleet boots with per-class lanes on
    (``--slo-classes interactive:...;batch:...``) and the deterministic
    scenarios from scripts/workloads.py replay against it:

      pin      preemption bit-identity, direct against one replica: a
               batch-class stream sized to saturate the KV page budget
               runs solo (the reference), then again with an interactive
               arrival forcing a chunk-boundary preemption — the
               preempted+resumed output must be byte-identical, with
               dllama_preemptions_total{outcome="resumed"} >= 1 and
               zero outcome="error"
      bursty   interactive bursts through the router while batch jobs
               saturate the batch lane: zero errors in either class and
               interactive TTFT p99 <= WORKLOADS_TTFT_P99_MS (default
               30000 — "bounded", with CPU CI slack, not a latency claim)
      mixed    long-context + multi-turn prefix reuse + abusive mid-SSE
               disconnects: zero errors outside the deliberate drops,
               and the fleet still answers afterwards
      kill     a replica SIGKILLed mid-burst with router checkpointing
               on: every stream still ends 200/[DONE]/no error event,
               and the router counted >= 1 ok resume

    Plus a federation gate: after the bursty mix, /metrics/fleet must
    carry the per-class gauge series (lane pressure is an operator
    surface, not replica-local state). BENCH_WORKLOADS_OUT writes the
    full report JSON for CI artifacts. The final metric line is the
    bursty-mix interactive TTFT p99; vs_baseline divides the unloaded
    interactive TTFT by it (below 1.0 = saturation costs latency)."""
    import http.client
    import importlib.util
    import shutil
    import signal
    import socket
    import tempfile
    import threading

    import numpy as np

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.tokenizer_file import (TokenizerData,
                                                   write_tokenizer)
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.quants import blocks
    from dllama_tpu.serving import fleet as fleet_mod
    from dllama_tpu.serving import router as router_mod

    repo = os.path.dirname(os.path.abspath(__file__))
    spec_wl = importlib.util.spec_from_file_location(
        "dllama_workloads", os.path.join(repo, "scripts", "workloads.py"))
    wl = importlib.util.module_from_spec(spec_wl)
    spec_wl.loader.exec_module(wl)

    bursts = max(2, min(n, 6))
    ttft_bound_ms = float(os.environ.get("WORKLOADS_TTFT_P99_MS", "30000"))
    # batch request budget deliberately past any row's room: admission
    # clamps steps to seq_len - plen, so ONE such row reserves exactly
    # half the 2-slot paged budget and TWO saturate it — the interactive
    # arrival then must preempt, whatever the chat template's overhead
    batch_steps = 450
    tmp = tempfile.mkdtemp(prefix="bench_workloads_")
    spec = ModelSpec(arch=ArchType.LLAMA, dim=128, hidden_dim=256,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=512,
                     seq_len=512, weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    model, tok = os.path.join(tmp, "m.m"), os.path.join(tmp, "t.t")
    write_model(model, spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(
                    np.float32) for e in tensor_plan(spec)})
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
             + [b"hi"] * (512 - 259))
    write_tokenizer(tok, TokenizerData(vocab=vocab, scores=[0.0] * 512,
                                       bos_id=1, eos_id=2))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("DLLAMA_FAULTS", None)

    def _free_base(span: int) -> int:
        for _ in range(64):
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                base = s.getsockname()[1]
            if base + span > 65500:
                continue
            try:
                for i in range(1, span):
                    with socket.socket() as t:
                        t.bind(("127.0.0.1", base + i))
                return base
            except OSError:
                continue
        raise RuntimeError("no free port span for the replica fleet")

    def _scrape(port, family, match=(), path="/metrics"):
        """Sum of the family's samples whose label text contains every
        ``match`` fragment."""
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10.0)
        try:
            conn.request("GET", path)
            text = conn.getresponse().read().decode()
        finally:
            conn.close()
        total = 0.0
        for line in text.splitlines():
            if line.startswith(family) and all(m in line for m in match):
                total += float(line.rsplit(" ", 1)[1])
        return total

    gates = []
    phases: dict = {}
    fl = fleet_mod.Fleet(
        model, tok, n_replicas=2, base_port=_free_base(2), host="127.0.0.1",
        # --batch-max 2 sizes the paged budget at 2*seq_len tokens (paged
        # rows are bounded by pages, not slots); --batch-chunk 2 makes
        # chunk-boundary preemption latency two tokens
        replica_args=["--batch-window", "5", "--batch-max", "2",
                      "--batch-chunk", "2", "--prefill-chunk", "64",
                      "--kv-pages", "16", "--tp", "1",
                      "--ckpt-interval", "2",
                      "--slo-classes",
                      "interactive:depth=32,deadline=240;batch:depth=8"],
        log_dir=os.path.join(tmp, "logs"), env=env, roles=["both", "both"])
    rstate = rsrv = None
    try:
        log("workloads bench: booting both+both fleet "
            f"(ports {[r.port for r in fl.replicas]})...")
        t0 = time.perf_counter()
        fl.start()
        if not fl.wait_ready(timeout_s=300.0):
            raise RuntimeError("replicas never became ready")
        log(f"fleet ready in {time.perf_counter() - t0:.1f}s")
        ports = [r.port for r in fl.replicas]

        # warm-up: compile every replica's programs outside the clocks;
        # the LAST warm request per replica doubles as the unloaded-TTFT
        # baseline sample
        base_ttfts = []
        for p in ports:
            for w in range(2):
                r = wl.do_request("127.0.0.1", p, wl.Req(
                    0.0, f"warm-{p}-{w}", "interactive",
                    [{"role": "user", "content": f"warm {w} up"}], 8),
                    timeout=300.0)
                if r["status"] != 200 or r["error"]:
                    raise RuntimeError(
                        f"warm-up on :{p} failed: {r['status']} "
                        f"{r['error']!r}")
                if w == 1 and r["ttft_ms"] is not None:
                    base_ttfts.append(r["ttft_ms"])
        baseline_ttft = _pct(base_ttfts, 50)

        # ---- pin: preemption bit-identity (replica 0, direct) --------
        p0 = ports[0]
        pin_req = wl.Req(0.0, "pin", "batch",
                         [{"role": "user",
                           "content": "pin me alpha bravo cedar delta"}],
                         batch_steps)
        fill_req = wl.Req(0.0, "fill", "batch",
                          [{"role": "user",
                            "content": "fill me echo fjord gamma haze"}],
                          batch_steps)
        solo = wl.do_request("127.0.0.1", p0, pin_req, timeout=600.0)
        if solo["status"] != 200 or solo["error"] or not solo["text"]:
            gates.append(f"pin solo run failed: {solo['status']} "
                         f"{solo['error']!r}")
            raise RuntimeError(gates[-1])
        res0 = _scrape(p0, "dllama_preemptions_total",
                       ('outcome="resumed"',))
        err0 = _scrape(p0, "dllama_preemptions_total",
                       ('outcome="error"',))
        slots = [None, None]
        # filler first, pin second: the preemptor exports the YOUNGEST
        # batch row, so the pin is the one parked and resumed
        t_fill = threading.Thread(target=lambda: slots.__setitem__(
            0, wl.do_request("127.0.0.1", p0, fill_req, timeout=600.0)),
            daemon=True)
        t_fill.start()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if _scrape(p0, "dllama_class_resident_rows",
                       ('slo_class="batch"',)) >= 1:
                break
            time.sleep(0.01)
        t_pin = threading.Thread(target=lambda: slots.__setitem__(
            1, wl.do_request("127.0.0.1", p0, pin_req, timeout=600.0)),
            daemon=True)
        t_pin.start()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if _scrape(p0, "dllama_class_resident_rows",
                       ('slo_class="batch"',)) >= 2:
                break
            time.sleep(0.01)
        inter = wl.do_request("127.0.0.1", p0, wl.Req(
            0.0, "pin-int", "interactive",
            [{"role": "user", "content": "quick question"}], 8),
            timeout=600.0)
        t_fill.join(timeout=600.0)
        t_pin.join(timeout=600.0)
        resumed = _scrape(p0, "dllama_preemptions_total",
                          ('outcome="resumed"',)) - res0
        perrs = _scrape(p0, "dllama_preemptions_total",
                        ('outcome="error"',)) - err0
        phases["pin"] = {"solo_len": len(solo["text"]),
                         "resumed": resumed, "preempt_errors": perrs,
                         "interactive_status": inter["status"]}
        if inter["status"] != 200 or inter["error"]:
            gates.append(f"interactive arrival failed during saturation: "
                         f"{inter['status']} {inter['error']!r}")
        if resumed < 1:
            gates.append("no preemption resumed during the pin phase — "
                         "the bit-identity comparison never exercised "
                         "the park/resume path")
        if perrs:
            gates.append(f"{perrs:.0f} preemption export errors")
        pinned = slots[1]
        if pinned is None or pinned["status"] != 200 or pinned["error"]:
            gates.append(f"pinned batch stream failed: {pinned!r}"[:300])
        elif pinned["text"] != solo["text"]:
            gates.append(
                "preempted batch output != unpreempted reference "
                f"(lens {len(pinned['text'])} vs {len(solo['text'])})")
        log(f"[pin] resumed {resumed:.0f}, errors {perrs:.0f}, "
            f"bit-identical={pinned is not None and pinned['text'] == solo['text']}")

        # ---- router up for the fleet phases --------------------------
        rstate = router_mod.RouterState(
            [router_mod.Replica("127.0.0.1", p) for p in ports],
            probe_interval_s=0.3, ckpt_interval=2)
        rstate.probe_once()
        rsrv = router_mod.create_router_server(rstate, "127.0.0.1", 0)
        threading.Thread(target=rsrv.serve_forever, daemon=True).start()
        rstate.start_probes()
        r_port = rsrv.server_address[1]

        # ---- bursty: interactive TTFT under a saturated batch lane ---
        sched = wl.bursty_mix(seed=11, bursts=bursts, burst_size=4,
                              gap_s=1.5, batch_jobs=2, batch_tokens=160,
                              interactive_tokens=12)
        results = wl.run_schedule("127.0.0.1", r_port, sched,
                                  timeout=600.0)
        summ = wl.summarize(results)
        phases["bursty"] = summ
        for cls in ("interactive", "batch"):
            for msg in summ.get(cls, {}).get("errors", []):
                gates.append(f"bursty {cls}: {msg}")
        ttft_p99 = (summ.get("interactive") or {}).get("ttft_p99_ms")
        if ttft_p99 is None:
            gates.append("bursty mix produced no interactive TTFT sample")
        elif ttft_p99 > ttft_bound_ms:
            gates.append(f"interactive TTFT p99 {ttft_p99:.0f} ms exceeds "
                         f"the {ttft_bound_ms:.0f} ms class bound under "
                         "the saturated batch lane")
        log(f"[bursty] {json.dumps(summ, sort_keys=True)}")
        # federation: the per-class gauges must be visible fleet-wide
        conn = http.client.HTTPConnection("127.0.0.1", r_port,
                                          timeout=10.0)
        try:
            conn.request("GET", "/metrics/fleet")
            fed = conn.getresponse().read().decode()
        finally:
            conn.close()
        for fam in ("dllama_class_queue_depth", "dllama_class_ttft_ms"):
            if fam not in fed:
                gates.append(f"{fam} missing from /metrics/fleet — "
                             "lane pressure is not federated")

        # ---- mixed: long-context + prefix reuse + mid-SSE drops ------
        mixed = (wl.long_context(seed=5, n=3, target_chars=280,
                                 max_tokens=16)
                 + wl.multi_turn(seed=3, conversations=2, turns=3,
                                 max_tokens=12)
                 + wl.abusive_disconnects(seed=9, n=3, max_tokens=64))
        msumm = wl.summarize(
            wl.run_schedule("127.0.0.1", r_port, mixed, timeout=600.0))
        phases["mixed"] = msumm
        for cls, c in msumm.items():
            for msg in c["errors"]:
                gates.append(f"mixed {cls}: {msg}")
        after = wl.do_request("127.0.0.1", r_port, wl.Req(
            0.0, "post-abuse", "interactive",
            [{"role": "user", "content": "still there?"}], 4),
            timeout=300.0)
        if after["status"] != 200 or after["error"]:
            gates.append("fleet unhealthy after the mid-SSE disconnects: "
                         f"{after['status']} {after['error']!r}")
        log(f"[mixed] {json.dumps(msumm, sort_keys=True)}")

        # ---- kill: SIGKILL a replica mid-burst -----------------------
        ok0 = rstate._m_resumes.value(outcome="ok")
        kres = [None] * 4
        killed = {}

        def _streamer(i, rq):
            kres[i] = wl.do_request("127.0.0.1", r_port, rq,
                                    timeout=600.0)

        # streams long enough that the kill lands mid-decode: past the
        # first router checkpoint (interval 2), well before [DONE]
        burst = wl.kill_burst(seed=13, n=4, max_tokens=160)
        th = [threading.Thread(target=_streamer, args=(i, rq),
                               daemon=True)
              for i, rq in enumerate(burst[:2])]
        for t in th:
            t.start()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            busy = [r for r in rstate.replicas
                    if r.snapshot().get("inflight", 0) > 0]
            if len(busy) >= 1 and sum(
                    r.snapshot().get("inflight", 0)
                    for r in rstate.replicas) >= 2:
                break
            time.sleep(0.01)
        time.sleep(0.3)  # let the first checkpoints land in the store
        for i, r in enumerate(rstate.replicas):
            if r.snapshot().get("inflight", 0) > 0:
                os.kill(fl.replicas[i].proc.pid, signal.SIGKILL)
                killed["replica"] = r.name
                log(f"[kill] SIGKILLed {r.name} mid-burst")
                break
        # the back half of the burst arrives AFTER the kill: routed (or
        # retried) onto the survivor without the client noticing
        th += [threading.Thread(target=_streamer, args=(2 + i, rq),
                                daemon=True)
               for i, rq in enumerate(burst[2:])]
        for t in th[2:]:
            t.start()
        for t in th:
            t.join(timeout=600.0)
        resumes = rstate._m_resumes.value(outcome="ok") - ok0
        phases["kill"] = {"killed": killed.get("replica"),
                          "resumes_ok": resumes,
                          "results": [
                              {"name": r["name"], "status": r["status"],
                               "done": r["done"], "error": r["error"]}
                              if r else None for r in kres]}
        if not killed:
            gates.append("no in-flight replica found to SIGKILL")
        for r in kres:
            if r is None or r["status"] != 200 or r["error"] \
                    or not r["done"]:
                gates.append(
                    "client-visible error across the kill: "
                    + (f"{r['name']}: {r['status']} {r['error']!r} "
                       f"done={r['done']}" if r else "stream never "
                       "resolved"))
        if killed and resumes < 1:
            gates.append("replica killed but the router counted no ok "
                         f"resume (got {resumes:.0f})")
        log(f"[kill] resumes ok {resumes:.0f}, "
            f"results {[r['status'] if r else None for r in kres]}")
    finally:
        if rstate is not None:
            rstate.stop_probes()
        if rsrv is not None:
            rsrv.shutdown()
            rsrv.server_close()
        fl.drain(timeout_s=10.0)
        shutil.rmtree(tmp, ignore_errors=True)

    report = {
        "bursts": bursts, "batch_steps": batch_steps,
        "ttft_bound_ms": ttft_bound_ms,
        "cpu_count": os.cpu_count(),
        # CPU smoke: class-lane correctness, preemption bit-identity and
        # chaos survival. The TTFT bound is a CI noise envelope — the
        # real interactive SLO is a hardware claim (numbers owed once
        # the TPU tunnel resolves; ROADMAP carried follow-up).
        "tpu_deltas_owed": True,
        "baseline_ttft_ms": (round(baseline_ttft, 3)
                             if baseline_ttft is not None else None),
        "interactive_ttft_p99_ms": (round(ttft_p99, 3)
                                    if ttft_p99 is not None else None),
        "phases": phases,
        "gates_failed": gates,
    }
    out_path = os.environ.get("BENCH_WORKLOADS_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2, default=str)
        log(f"report written to {out_path}")
    result = {
        "metric": "smoke_workloads_ttft_ms",
        "value": round(ttft_p99, 3) if ttft_p99 is not None else None,
        "unit": "ms",
        "vs_baseline": (round(baseline_ttft / ttft_p99, 2)
                        if ttft_p99 and baseline_ttft else None),
        "baseline": "unloaded interactive TTFT p50 on the same fleet "
                    "(warm replicas, empty lanes)",
        "weights": "q40-workloads-fleet2",
        "platform": "cpu-subprocess-fleet",
        "n_devices": 2,
    }
    if gates:
        result["error"] = "; ".join(gates)
    return result


def run_elastic_bench(n: int) -> dict:
    """BENCH_ELASTIC=N: the closed-loop elastic fleet vs a static fleet
    on the same bursty-diurnal replay, jax-free IN THIS PROCESS (replicas
    are `cli serve` subprocesses pinned to CPU).

    Leg 1 (elastic): a 1-replica fleet with the autoscale supervisor on
    (min 1 / max 2, aggressive thresholds sized to the burst shape)
    serves ``scripts/workloads.py diurnal`` — busy burst windows
    alternating with idle troughs. The policy must scale up into the
    bursts (pre-warming the joining replica from the hot prefix) and
    shed back down in the troughs. Replica-seconds are integrated from
    0.1 s samples of the router's registered-replica count.

    Leg 2 (chaos): with both replicas up, a live SSE stream's replica is
    force-retired and then SIGKILLed MID-DRAIN — the stream must still
    end 200/[DONE]/error-free via the router's checkpoint resume, with
    ``drain_killed`` counted.

    Leg 3 (static): a fixed 2-replica fleet replays the same schedule.

    Gates (each failure lands in result["error"]):
      * the policy drove >= 1 scale-up AND >= 1 scale-down
        (policy decisions counted, joined/retired events counted)
      * zero client-visible errors in EVERY leg, chaos stream included
      * both legs meet the interactive TTFT p99 envelope
        (ELASTIC_TTFT_P99_MS, default 30000 — equal-SLO, CPU slack)
      * elastic replica-seconds STRICTLY below static on the same replay
      * the chaos leg counted >= 1 ok resume and >= 1 drain_killed

    BENCH_ELASTIC_OUT writes the full report JSON for CI artifacts. The
    final metric is elastic replica-seconds; vs_baseline divides the
    static fleet's replica-seconds by it (above 1.0 = elasticity saved
    capacity at equal SLO compliance)."""
    import importlib.util
    import shutil
    import signal
    import socket
    import tempfile
    import threading

    import numpy as np

    from dllama_tpu.formats.spec import ArchType, ModelSpec
    from dllama_tpu.formats.tokenizer_file import (TokenizerData,
                                                   write_tokenizer)
    from dllama_tpu.formats.weights import tensor_plan, write_model
    from dllama_tpu.quants import blocks
    from dllama_tpu.serving import autoscale as asc
    from dllama_tpu.serving import fleet as fleet_mod
    from dllama_tpu.serving import router as router_mod

    repo = os.path.dirname(os.path.abspath(__file__))
    spec_wl = importlib.util.spec_from_file_location(
        "dllama_workloads", os.path.join(repo, "scripts", "workloads.py"))
    wl = importlib.util.module_from_spec(spec_wl)
    spec_wl.loader.exec_module(wl)

    # >= 3 diurnal cycles: the LAST burst always triggers a scale-up
    # whose boot cost the replay tail pays without reaping the benefit
    # (the replay ends before the newcomer does useful work) — a one-off
    # artifact that dominates a 2-cycle replay but amortizes over the
    # troughs, where elasticity actually earns its keep
    cycles = max(3, min(n, 4))
    ttft_bound_ms = float(os.environ.get("ELASTIC_TTFT_P99_MS", "30000"))
    tmp = tempfile.mkdtemp(prefix="bench_elastic_")
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=96,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=300,
                     seq_len=96, weights_float_type=blocks.Q40)
    rng = np.random.default_rng(0)
    model, tok = os.path.join(tmp, "m.m"), os.path.join(tmp, "t.t")
    write_model(model, spec,
                {e.name: 0.05 * rng.standard_normal(e.d * e.n).astype(
                    np.float32) for e in tensor_plan(spec)})
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
             + [b"hi"] * 41)
    write_tokenizer(tok, TokenizerData(vocab=vocab, scores=[0.0] * 300,
                                       bos_id=1, eos_id=2))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # slow every SSE frame a little so streams outlive the policy's tick
    # cadence and the chaos SIGKILL lands squarely inside a live stream
    env["DLLAMA_FAULTS"] = "stream:slow:delay_ms=30"

    def _free_base(span: int) -> int:
        for _ in range(64):
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                base = s.getsockname()[1]
            if base + span > 65500:
                continue
            try:
                for i in range(1, span):
                    with socket.socket() as t:
                        t.bind(("127.0.0.1", base + i))
                return base
            except OSError:
                continue
        raise RuntimeError("no free port span for the replica fleet")

    replica_args = ["--batch-window", "5", "--batch-max", "2",
                    "--batch-chunk", "2", "--kv-pages", "16", "--tp", "1",
                    "--ckpt-interval", "2"]
    schedule_kw = dict(cycles=cycles, bursts_per_cycle=3, burst_size=4,
                       burst_gap_s=1.5, idle_s=16.0, max_tokens=24)

    def integrate(samples, t0, t1) -> float:
        """Replica-seconds: piecewise-constant integral of the sampled
        registered count over [t0, t1]."""
        total, prev_t, prev_v = 0.0, None, None
        for t, v in samples + [(t1, samples[-1][1] if samples else 0)]:
            t = min(max(t, t0), t1)
            if prev_t is not None:
                total += prev_v * (t - prev_t)
            prev_t, prev_v = t, v
        return total

    def boot(n_replicas: int, base_port: int):
        fl = fleet_mod.Fleet(
            model, tok, n_replicas=n_replicas, base_port=base_port,
            host="127.0.0.1", replica_args=replica_args,
            log_dir=os.path.join(tmp, f"logs-{base_port}"), env=env)
        fl.start()
        if not fl.wait_ready(timeout_s=300.0):
            raise RuntimeError("replicas never became ready")
        fl.start_supervision(interval_s=0.5)
        state = router_mod.RouterState(
            [router_mod.Replica("127.0.0.1", r.port) for r in fl.replicas],
            probe_interval_s=0.25, ckpt_interval=2)
        state.probe_once()
        state.start_probes()
        srv = router_mod.create_router_server(state, "127.0.0.1", 0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        # compile the boot replicas' programs outside the clocks
        for r in fl.replicas:
            w = wl.do_request("127.0.0.1", srv.server_address[1], wl.Req(
                0.0, f"warm-{r.port}", "interactive",
                [{"role": "user", "content": "warm up"}], 4), timeout=300.0)
            if w["status"] != 200 or w["error"]:
                raise RuntimeError(f"warm-up failed: {w['status']} "
                                   f"{w['error']!r}")
        return fl, state, srv

    gates = []
    report: dict = {"cycles": cycles, "ttft_bound_ms": ttft_bound_ms,
                    "cpu_count": os.cpu_count()}
    elastic_rs = static_rs = None

    # ---- leg 1+2: the elastic fleet ----------------------------------
    fl = state = srv = sup = None
    try:
        log("elastic bench: booting 1-replica fleet + autoscale loop...")
        fl, state, srv = boot(1, _free_base(4))
        r_port = srv.server_address[1]
        cfg = asc.PolicyConfig(
            min_replicas=1, max_replicas=2, up_pressure=0.5,
            down_pressure=0.2, up_consecutive=2, down_consecutive=4,
            cooldown_up_s=2.0, cooldown_down_s=3.0)
        sup = fleet_mod.ElasticSupervisor(
            fl, state, asc.AutoscalePolicy(cfg), interval_s=0.25,
            ready_timeout_s=120.0, drain_timeout_s=20.0, prewarm_tokens=8)
        ups0 = state._m_policy_evals.value(decision="up")
        downs0 = state._m_policy_evals.value(decision="down")
        joined0 = state._m_scale_events.value(event="joined")
        retired0 = state._m_scale_events.value(event="retired")
        fallback0 = state._m_scale_events.value(event="prewarm_fallback")
        sup.start()

        samples = []
        stop_sampling = threading.Event()

        def _sampler():
            while not stop_sampling.is_set():
                samples.append((time.monotonic(),
                                state._count_registered()))
                time.sleep(0.1)

        threading.Thread(target=_sampler, daemon=True).start()
        sched = wl.diurnal(seed=7, **schedule_kw)
        t0 = time.monotonic()
        results = wl.run_schedule("127.0.0.1", r_port, sched, timeout=600.0)
        t1 = time.monotonic()
        stop_sampling.set()
        elastic_rs = integrate(samples, t0, t1)
        summ = wl.summarize(results)
        ups = state._m_policy_evals.value(decision="up") - ups0
        downs = state._m_policy_evals.value(decision="down") - downs0
        joined = state._m_scale_events.value(event="joined") - joined0
        retired = state._m_scale_events.value(event="retired") - retired0
        fallback = (state._m_scale_events.value(event="prewarm_fallback")
                    - fallback0)
        report["elastic"] = {
            "replica_seconds": round(elastic_rs, 1),
            "wall_s": round(t1 - t0, 1), "summary": summ,
            "policy_ups": ups, "policy_downs": downs,
            "joined": joined, "retired": retired,
            "prewarm_fallbacks": fallback,
        }
        for cls, c in summ.items():
            for msg in c["errors"]:
                gates.append(f"elastic {cls}: {msg}")
        e_p99 = (summ.get("interactive") or {}).get("ttft_p99_ms")
        if e_p99 is None:
            gates.append("elastic replay produced no TTFT sample")
        elif e_p99 > ttft_bound_ms:
            gates.append(f"elastic TTFT p99 {e_p99:.0f} ms exceeds the "
                         f"{ttft_bound_ms:.0f} ms envelope — not "
                         "equal-SLO, the replica-seconds win is void")
        if ups < 1 or joined < 1:
            gates.append("the policy never scaled up into a burst "
                         f"(decisions up={ups:.0f}, joined={joined:.0f})")
        if downs < 1 or retired < 1:
            gates.append("the policy never scaled down in a trough "
                         f"(decisions down={downs:.0f}, "
                         f"retired={retired:.0f})")
        log(f"[elastic] replica-seconds {elastic_rs:.1f} over "
            f"{t1 - t0:.1f}s wall; ups {ups:.0f} downs {downs:.0f} "
            f"prewarm_fallbacks {fallback:.0f}")

        # the loop may be mid-transition (a tail-burst scale-up still
        # booting): stop new policy ticks, then wait out the in-flight
        # transition before staging the chaos leg — otherwise the
        # SIGKILL below lands on an unmanaged (not-yet-retiring)
        # replica, the crash-restart supervisor resurrects it mid-gate,
        # and the resume finds no ACTIVE sibling
        sup.stop()
        if sup._lock.acquire(timeout=240.0):
            sup._lock.release()
        else:
            gates.append("a scale transition never settled before the "
                         "chaos leg")

        # ---- leg 2: SIGKILL mid-drain on a live stream ---------------
        if state._count_registered() < 2:
            sup.scale_up()  # forced: the chaos leg needs a sibling
        if state._count_registered() < 2:
            gates.append("could not restore a 2-replica fleet for the "
                         "chaos leg")
        else:
            ok0 = state._m_resumes.value(outcome="ok")
            dk0 = state._m_scale_events.value(event="drain_killed")
            chaos_res = [None]

            def _chaos_stream():
                chaos_res[0] = wl.do_request(
                    "127.0.0.1", r_port, wl.Req(
                        0.0, "chaos", "interactive",
                        [{"role": "user",
                          "content": "chaos stream ride the drain"}], 64),
                    timeout=600.0)

            ct = threading.Thread(target=_chaos_stream, daemon=True)
            ct.start()
            victim = None
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and victim is None:
                for rep in state.replicas:
                    if rep.snapshot().get("inflight", 0) > 0:
                        victim = rep.name
                        break
                time.sleep(0.01)
            if victim is None:
                gates.append("chaos stream never showed up in-flight")
            else:
                time.sleep(0.3)  # let checkpoints land in the store
                proc = next(p for p in fl.replicas if p.name == victim)
                dt = threading.Thread(
                    target=lambda: sup.scale_down(target=victim),
                    daemon=True)
                dt.start()
                time.sleep(0.3)  # drain under way (SIGTERM delivered)
                if proc.proc.poll() is None:
                    os.kill(proc.proc.pid, signal.SIGKILL)
                    log(f"[chaos] SIGKILLed {victim} mid-drain")
                dt.join(timeout=120.0)
            ct.join(timeout=600.0)
            cres = chaos_res[0]
            resumes = state._m_resumes.value(outcome="ok") - ok0
            drain_killed = (state._m_scale_events.value(
                event="drain_killed") - dk0)
            report["chaos"] = {
                "victim": victim, "resumes_ok": resumes,
                "drain_killed": drain_killed,
                "stream": ({"status": cres["status"], "done": cres["done"],
                            "error": cres["error"]} if cres else None)}
            if cres is None or cres["status"] != 200 or cres["error"] \
                    or not cres["done"]:
                gates.append(
                    "client-visible damage across the mid-drain SIGKILL: "
                    + (f"{cres['status']} {cres['error']!r} "
                       f"done={cres['done']}" if cres
                       else "stream never resolved"))
            if victim and resumes < 1:
                gates.append("mid-drain SIGKILL but no ok resume counted "
                             f"(got {resumes:.0f})")
            if victim and drain_killed < 1:
                gates.append("mid-drain SIGKILL not counted as "
                             "drain_killed")
            log(f"[chaos] resumes ok {resumes:.0f}, "
                f"drain_killed {drain_killed:.0f}")
    finally:
        if sup is not None:
            sup.stop()
        if state is not None:
            state.stop_probes()
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if fl is not None:
            fl.drain(timeout_s=10.0)

    # ---- leg 3: the static 2-replica fleet on the same replay --------
    fl = state = srv = None
    try:
        log("elastic bench: booting the static 2-replica fleet...")
        fl, state, srv = boot(2, _free_base(4))
        sched = wl.diurnal(seed=7, **schedule_kw)
        t0 = time.monotonic()
        results = wl.run_schedule("127.0.0.1", srv.server_address[1],
                                  sched, timeout=600.0)
        t1 = time.monotonic()
        static_rs = 2.0 * (t1 - t0)
        ssumm = wl.summarize(results)
        report["static"] = {"replica_seconds": round(static_rs, 1),
                            "wall_s": round(t1 - t0, 1), "summary": ssumm}
        for cls, c in ssumm.items():
            for msg in c["errors"]:
                gates.append(f"static {cls}: {msg}")
        s_p99 = (ssumm.get("interactive") or {}).get("ttft_p99_ms")
        if s_p99 is not None and s_p99 > ttft_bound_ms:
            gates.append(f"static TTFT p99 {s_p99:.0f} ms exceeds the "
                         f"{ttft_bound_ms:.0f} ms envelope")
        log(f"[static] replica-seconds {static_rs:.1f} over "
            f"{t1 - t0:.1f}s wall")
    finally:
        if state is not None:
            state.stop_probes()
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if fl is not None:
            fl.drain(timeout_s=10.0)
        shutil.rmtree(tmp, ignore_errors=True)

    if elastic_rs is not None and static_rs is not None \
            and elastic_rs >= static_rs:
        gates.append(
            f"elastic fleet used {elastic_rs:.1f} replica-seconds vs the "
            f"static fleet's {static_rs:.1f} on the same replay — "
            "elasticity saved nothing")
    report["gates_failed"] = gates
    out_path = os.environ.get("BENCH_ELASTIC_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2, default=str)
        log(f"report written to {out_path}")
    result = {
        "metric": "smoke_elastic_replica_seconds",
        "value": round(elastic_rs, 1) if elastic_rs is not None else None,
        "unit": "replica_s",
        "vs_baseline": (round(static_rs / elastic_rs, 2)
                        if elastic_rs and static_rs else None),
        "baseline": "a static 2-replica fleet on the same bursty-diurnal "
                    "replay (equal SLO envelope)",
        "weights": "q40-elastic-fleet",
        "platform": "cpu-subprocess-fleet",
        "n_devices": 2,
    }
    if gates:
        result["error"] = "; ".join(gates)
    return result


def run_c10k_bench(n: int) -> dict:
    """BENCH_C10K=N: N concurrent slow-drip SSE sessions through ONE
    event-loop router with adversarial chaos peers running alongside —
    jax-free and fully in-process (the replicas are evloop stub servers,
    not engines: this bench measures the DATA PLANE, not decode).

    Topology: 2 stub replicas (selectors loops) <- the router's evloop
    front door <- N well-behaved SSE clients on sharded selectors loops,
    PLUS a chaos cohort (scripts/chaos_peer.py: slow-loris dribblers,
    midstream-hang readers fed a firehose, RST peers) PLUS one mid-SSE
    STALL session whose upstream goes silent right after a checkpoint
    frame and must be checkpoint-resumed on the sibling byte-identically
    (dllama_stream_resume_total{outcome="stall"}).

    Every event carries the replica's monotonic send stamp, so "added
    latency" is exactly the router + scheduling cost, not the drip.
    N is scaled down only when RLIMIT_NOFILE demands it (~5 fds per
    session across the four sockets each one fans out to).

    Gates (each failure lands in result["error"]):
      * zero client-visible errors on the well-behaved cohort, chaos on
      * peak concurrent streams >= 0.9 * N (the sessions truly overlap)
      * p99 added event latency <= C10K_P99_MS (default 2000 ms)
      * RSS growth <= max(N * C10K_RSS_KB (default 64 KiB), 192 MiB)
      * the stall session's body is EXACTLY the no-failure stream and
        the resume was accounted with outcome="stall"
      * every chaos mode bit: slow-loris cut at --header-timeout,
        midstream-hang killed at --client-stall-timeout, RST absorbed —
        and the router still answers /health afterwards
      * admission control: a --max-conns 4 router sheds connection 5
        with the canned 503 BEFORE allocating state (reason=max_conns)

    BENCH_C10K_OUT writes the full report JSON for CI artifacts."""
    import base64
    import http.client as hc
    import importlib.util
    import resource
    import socket
    import threading

    from dllama_tpu.serving import evloop
    from dllama_tpu.serving import router as router_mod
    from dllama_tpu.serving.protocol import HDR_RESUME_OFFSET

    # ---- fd budget: ~5 fds per session (client sock, router front +
    # upstream, replica sock, slack) — raise the soft limit, then scale
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
            soft = hard
        except (ValueError, OSError):
            pass
    n_eff = max(8, min(n, (soft - 512) // 5))
    if n_eff < n:
        log(f"c10k: RLIMIT_NOFILE {soft} caps the run at {n_eff} "
            f"sessions (asked {n})")

    # ---- pacing: ramp at a bounded accept rate; drip slowly enough that
    # the single-process GIL can push every event through all three hops
    rate = max(100.0, float(os.environ.get("C10K_RAMP_RATE", "1500")))
    ramp_s = n_eff / rate
    drip_s = max(0.4, n_eff / 6000.0)
    n_events = max(8, min(60, int((ramp_s + 3.0) / drip_s) + 2))
    # the inter-byte stall budget must clear one drip interval with slack
    stall_timeout_s = drip_s * 2.0 + 1.0

    # ---- the stall-session fixture: what the client must end up with
    ev_a = b"data: alpha\n\n"
    ev_b = b"data: bravo\n\n"
    ev_c = b"data: charlie\n\n"
    sse_done = b"data: [DONE]\n\n"
    visible = ev_a + ev_b + ev_c + sse_done
    snap = b"c10k-stall-snapshot"
    ckpt_off = len(ev_a)
    ckpt_frame = (b"event: dllama-ckpt\ndata: %d %s\n\n"
                  % (ckpt_off, base64.b64encode(snap)))
    resume_bodies: list = []

    # ---- stub replica: /ready, slow-drip SSE chat (send-stamped), the
    # stall session, a firehose for the hanging chaos readers, resume
    def stub_handler(server, sock, addr):
        buf = bytearray()
        while True:
            req = yield from evloop.read_request(sock, buf)
            if req is None:
                return
            if req.method == "GET" and req.path == "/ready":
                body = json.dumps({
                    "status": "ready", "slots_occupied": 0,
                    "slots_total": 65536, "queue_depth": 0,
                    "kv_pages_free": 65536, "kv_pages_total": 65536,
                    "prefix_hit_rate": 0.0}).encode()
                yield from evloop.send_all(sock, evloop.response_bytes(
                    200, [("Content-Type", "application/json"),
                          ("Content-Length", str(len(body)))], body))
            elif req.method == "POST" and req.path == "/v1/kv/resume":
                resume_bodies.append(req.body)
                cont = visible[ckpt_off:]
                yield from evloop.send_all(sock, evloop.response_bytes(
                    200, [("Content-Type", "text/event-stream"),
                          (HDR_RESUME_OFFSET, str(ckpt_off)),
                          ("Content-Length", str(len(cont)))], cont))
            elif req.method == "POST":
                head = evloop.response_bytes(
                    200, [("Content-Type", "text/event-stream"),
                          ("Connection", "close")])
                if b"stall-session" in req.body:
                    # checkpoint, one more event, then SILENCE with the
                    # socket open: the death only the stall budget sees
                    yield from evloop.send_all(
                        sock, head + ev_a + ckpt_frame + ev_b)
                    yield from evloop.sleep(120.0)
                    return
                if b"chaos" in req.body:
                    # firehose for midstream-hang peers: the bounded
                    # relay buffer pauses THIS send (backpressure) until
                    # the client-stall kill tears the path down (OSError
                    # here ends the task — the loop treats that as the
                    # normal teardown)
                    yield from evloop.send_all(sock, head)
                    block = b"data: " + b"x" * 8192 + b"\n\n"
                    while True:
                        yield from evloop.send_all(sock, block)
                yield from evloop.send_all(sock, head)
                for k in range(n_events):
                    yield from evloop.sleep(drip_s)
                    ev = (b"data: " + json.dumps(
                        {"k": k, "t_us": int(time.monotonic() * 1e6)}
                    ).encode() + b"\n\n")
                    yield from evloop.send_all(sock, ev)
                yield from evloop.send_all(sock, b"data: [DONE]\n\n")
                return
            else:
                yield from evloop.send_all(sock, evloop.response_bytes(
                    404, [("Content-Length", "0")]))
            if not req.keep_alive:
                return

    def boot_stub(name: str):
        srv = evloop.EventLoopServer(("127.0.0.1", 0), stub_handler)
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name=f"c10k-replica-{name}").start()
        return srv

    def _rss_kb() -> int:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1])
        except OSError:
            pass
        return 0

    def _drain(sock, timeout: float) -> bytes:
        sock.settimeout(timeout)
        out = bytearray()
        try:
            while True:
                b = sock.recv(65536)
                if not b:
                    break
                out += b
        except OSError:
            pass
        return bytes(out)

    repo = os.path.dirname(os.path.abspath(__file__))
    spec_cp = importlib.util.spec_from_file_location(
        "dllama_chaos_peer", os.path.join(repo, "scripts", "chaos_peer.py"))
    chaos = importlib.util.module_from_spec(spec_cp)
    spec_cp.loader.exec_module(chaos)

    gates: list = []
    report: dict = {"n_requested": n, "n_sessions": n_eff,
                    "events_per_session": n_events,
                    "drip_s": drip_s, "ramp_s": round(ramp_s, 2),
                    "stall_timeout_s": stall_timeout_s}
    rep_a = rep_b = state = srv = None
    stop_mon = threading.Event()
    shards: list = []
    try:
        rep_a, rep_b = boot_stub("a"), boot_stub("b")
        state = router_mod.RouterState(
            [router_mod.Replica("127.0.0.1", rep_a.server_address[1]),
             router_mod.Replica("127.0.0.1", rep_b.server_address[1])],
            probe_interval_s=3600.0, connect_timeout_s=5.0,
            header_timeout_s=3.0, first_byte_timeout_s=15.0,
            stall_timeout_s=stall_timeout_s, client_stall_timeout_s=2.0,
            ckpt_interval=2, probe_read_timeout_s=2.0)
        srv = router_mod.create_router_server(state, "127.0.0.1", 0)
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="c10k-router").start()
        port = srv.server_address[1]
        ready0 = state.probe_once()
        if ready0 != 2:
            gates.append(f"boot probe saw {ready0}/2 stub replicas ready")
        log(f"c10k: router on :{port}, {n_eff} sessions x {n_events} "
            f"events, drip {drip_s:.2f}s, ramp {ramp_s:.1f}s")

        # ---- the well-behaved cohort: sharded selectors client loops
        n_shards = 4 if n_eff >= 1000 else 2
        for i in range(n_shards):
            shards.append({"loop": evloop.Loop(), "count": 0, "done": 0,
                           "active": 0, "errors": 0, "err_samples": [],
                           "lats": []})

        def make_session(shard, gidx):
            def session():
                counted = False
                sock = None
                try:
                    yield from evloop.sleep(gidx / rate)
                    dl = time.monotonic() + 60.0
                    sock = yield from evloop.dial(("127.0.0.1", port), dl)
                    up = evloop.Upstream(sock, "127.0.0.1", port)
                    body = json.dumps({
                        "model": "m", "stream": True,
                        "messages": [{"role": "user",
                                      "content": f"c10k-{gidx}"}]}).encode()
                    yield from up.request(
                        "POST", "/v1/chat/completions",
                        {"Content-Type": "application/json"}, body, dl)
                    resp = yield from up.get_response(dl)
                    if resp.status != 200:
                        raise OSError(f"status {resp.status}")
                    shard["active"] += 1
                    counted = True
                    buf = bytearray()
                    seen_done, n_ev = False, 0
                    while not seen_done:
                        data = yield from resp.read_some(
                            time.monotonic() + drip_s + 10.0)
                        if not data:
                            break
                        now_us = time.monotonic() * 1e6
                        buf += data
                        while True:
                            cut = buf.find(b"\n\n")
                            if cut < 0:
                                break
                            frame = bytes(buf[:cut])
                            del buf[:cut + 2]
                            if frame == b"data: [DONE]":
                                seen_done = True
                            elif frame.startswith(b"data: {"):
                                stamp = json.loads(frame[6:])
                                shard["lats"].append(
                                    (now_us - stamp["t_us"]) / 1000.0)
                                n_ev += 1
                    if not seen_done or n_ev != n_events:
                        raise OSError(f"incomplete stream: done="
                                      f"{seen_done} events {n_ev}"
                                      f"/{n_events}")
                except Exception as e:  # noqa: BLE001 — every failure gates
                    shard["errors"] += 1
                    if len(shard["err_samples"]) < 5:
                        shard["err_samples"].append(
                            f"{type(e).__name__}: {e}")
                finally:
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                    if counted:
                        shard["active"] -= 1
                    shard["done"] += 1
                    if shard["done"] == shard["count"]:
                        shard["loop"].stop()
            return session()

        for gidx in range(n_eff):
            shards[gidx % n_shards]["count"] += 1
        for gidx in range(n_eff):
            sh = shards[gidx % n_shards]
            sh["loop"].spawn(make_session(sh, gidx))

        base_rss = _rss_kb()
        peak = {"active": 0, "rss_kb": base_rss}

        def monitor():
            while not stop_mon.is_set():
                act = sum(sh["active"] for sh in shards)
                peak["active"] = max(peak["active"], act)
                peak["rss_kb"] = max(peak["rss_kb"], _rss_kb())
                stop_mon.wait(0.1)

        mon = threading.Thread(target=monitor, daemon=True)
        mon.start()

        # ---- chaos cohorts + the stall session, live during the ramp
        n_peers = max(5, min(20, n_eff // 50))
        chaos_dur = max(8.0, ramp_s + 4.0)
        chaos_out: dict = {}
        chaos_threads = [
            threading.Thread(
                target=lambda m=mode: chaos_out.__setitem__(
                    m, chaos.run_cohort(m, "127.0.0.1", port, n_peers,
                                        chaos_dur)),
                daemon=True, name=f"c10k-chaos-{mode}")
            for mode in ("slowloris", "midstream_hang", "reset")]
        stall_out: dict = {}

        def run_stall():
            time.sleep(min(2.0, ramp_s / 2 + 0.2))
            try:
                conn = hc.HTTPConnection("127.0.0.1", port, timeout=90)
                conn.request(
                    "POST", "/v1/chat/completions",
                    json.dumps({"model": "m", "stream": True,
                                "messages": [{"role": "user",
                                              "content": "stall-session"}]
                                }).encode(),
                    headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                stall_out["status"] = resp.status
                stall_out["body"] = resp.read()
                conn.close()
            except Exception as e:  # noqa: BLE001 — gated below
                stall_out["error"] = f"{type(e).__name__}: {e}"

        stall_thread = threading.Thread(target=run_stall, daemon=True)

        shard_threads = [
            threading.Thread(target=sh["loop"].run, daemon=True,
                             name=f"c10k-shard-{i}")
            for i, sh in enumerate(shards)]
        t0 = time.monotonic()
        for t in shard_threads + chaos_threads + [stall_thread]:
            t.start()
        join_budget = ramp_s + n_events * drip_s + 90.0
        for t in shard_threads:
            t.join(max(10.0, join_budget - (time.monotonic() - t0)))
        for sh in shards:
            sh["loop"].call_threadsafe(sh["loop"].stop)  # no-op if done
        for t in chaos_threads:
            t.join(30.0)
        stall_thread.join(120.0)
        stop_mon.set()
        mon.join(5.0)
        wall_s = time.monotonic() - t0

        # ---- gates ----------------------------------------------------
        total_err = sum(sh["errors"] for sh in shards)
        total_done = sum(sh["done"] for sh in shards)
        samples = [s for sh in shards for s in sh["err_samples"]][:5]
        if total_err:
            gates.append(f"{total_err} well-behaved client error(s), "
                         f"e.g. {samples}")
        if total_done != n_eff:
            gates.append(f"only {total_done}/{n_eff} sessions finished "
                         f"inside {join_budget:.0f}s")
        if peak["active"] < 0.9 * n_eff:
            gates.append(f"peak concurrency {peak['active']} never "
                         f"reached 0.9 x {n_eff} — sessions did not "
                         "overlap")
        lats = [x for sh in shards for x in sh["lats"]]
        p50 = _pct(lats, 50) if lats else None
        p99 = _pct(lats, 99) if lats else None
        p99_bound = float(os.environ.get("C10K_P99_MS", "2000"))
        if p99 is None:
            gates.append("no event latencies recorded")
        elif p99 > p99_bound:
            gates.append(f"p99 added event latency {p99:.0f} ms exceeds "
                         f"the {p99_bound:.0f} ms budget")
        rss_growth_kb = max(0, peak["rss_kb"] - base_rss)
        rss_budget_kb = max(
            n_eff * float(os.environ.get("C10K_RSS_KB", "64")),
            192 * 1024)
        if rss_growth_kb > rss_budget_kb:
            gates.append(f"RSS grew {rss_growth_kb} KiB "
                         f"(> {rss_budget_kb:.0f} KiB budget)")
        if stall_out.get("status") != 200:
            gates.append(f"stall session: {stall_out}")
        elif stall_out.get("body") != visible:
            gates.append("stall session body is not byte-identical to "
                         "the no-failure stream "
                         f"({len(stall_out.get('body') or b'')} vs "
                         f"{len(visible)} bytes)")
        if state._m_resumes.value(outcome="stall") < 1:
            gates.append("no resume was accounted with outcome=stall")
        if snap not in resume_bodies:
            gates.append("the sibling never received the checkpoint "
                         "snapshot on /v1/kv/resume")
        for mode, key in (("slowloris", "cut_by_router"),
                          ("midstream_hang", "killed_by_router"),
                          ("reset", "sent_rst")):
            got = (chaos_out.get(mode) or {}).get(key, 0)
            if got < 1:
                gates.append(f"chaos {mode}: {key}=0 of {n_peers} peers "
                             f"({chaos_out.get(mode)})")
        try:
            conn = hc.HTTPConnection("127.0.0.1", port, timeout=10)
            conn.request("GET", "/health")
            health = conn.getresponse().status
            conn.close()
        except OSError as e:
            health = f"unreachable: {e}"
        if health != 200:
            gates.append(f"router /health after chaos: {health}")

        report.update({
            "wall_s": round(wall_s, 1), "peak_active": peak["active"],
            "sessions_done": total_done, "client_errors": total_err,
            "error_samples": samples,
            "added_latency_ms": {"p50": p50, "p99": p99,
                                 "n_events": len(lats)},
            "rss_base_kb": base_rss, "rss_peak_kb": peak["rss_kb"],
            "rss_growth_kb": rss_growth_kb,
            "rss_per_conn_kb": round(rss_growth_kb / n_eff, 1),
            "chaos": chaos_out,
            "stall": {"status": stall_out.get("status"),
                      "byte_identical":
                          stall_out.get("body") == visible,
                      "error": stall_out.get("error"),
                      "resume_outcome_stall":
                          state._m_resumes.value(outcome="stall")},
            "router_health_after": health,
        })
        log(f"c10k: {total_done}/{n_eff} sessions, peak {peak['active']} "
            f"concurrent, p99 added {p99 if p99 is None else round(p99)} "
            f"ms, +{rss_growth_kb} KiB RSS over {wall_s:.1f}s")

        # ---- admission-control proof on a tiny --max-conns router ------
        mini_state = router_mod.RouterState(
            [router_mod.Replica("127.0.0.1", rep_a.server_address[1])],
            probe_interval_s=3600.0, max_conns=4)
        mini = router_mod.create_router_server(mini_state, "127.0.0.1", 0)
        threading.Thread(target=mini.serve_forever, daemon=True,
                         name="c10k-mini-router").start()
        held = []
        try:
            for _ in range(4):
                c = hc.HTTPConnection("127.0.0.1",
                                      mini.server_address[1], timeout=10)
                c.request("GET", "/health")
                c.getresponse().read()
                held.append(c)  # keep-alive: the slot stays occupied
            s = socket.create_connection(
                ("127.0.0.1", mini.server_address[1]), timeout=10)
            data = _drain(s, timeout=5.0)
            s.close()
            got_503 = b"503" in data.split(b"\r\n", 1)[0]
            sheds = mini_state._m_sheds.value(reason="max_conns")
            report["shed"] = {"got_503": got_503, "sheds": sheds}
            if not got_503 or sheds < 1:
                gates.append(f"max-conns shed proof failed: 503="
                             f"{got_503} sheds={sheds} "
                             f"({data[:80]!r})")
        finally:
            for c in held:
                c.close()
            mini_state.stop_probes()
            mini.shutdown()
            mini.server_close()
    finally:
        stop_mon.set()
        for sh in shards:
            try:
                sh["loop"].call_threadsafe(sh["loop"].stop)
            except Exception:  # noqa: BLE001 — loop already torn down
                pass
        if state is not None:
            state.stop_probes()
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        for rep in (rep_a, rep_b):
            if rep is not None:
                rep.shutdown()
                rep.server_close()

    report["gates_failed"] = gates
    out_path = os.environ.get("BENCH_C10K_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2, default=str)
        log(f"report written to {out_path}")
    result = {
        "metric": "smoke_c10k_conns",
        "value": n_eff,
        "unit": "conns",
        "vs_baseline": None,
        "baseline": "the same process's thread-per-connection ceiling "
                    "(a threaded data plane cannot hold this many "
                    "concurrent SSE relays at bounded RSS)",
        "weights": "none-data-plane-only",
        "platform": "cpu-evloop",
        "n_devices": 2,
    }
    if gates:
        result["error"] = "; ".join(gates)
    return result


def _trajectory_note(status: str, result=None, error=None) -> None:
    """Append this round to the durable bench trajectory
    (results/trajectory.jsonl) and surface comparator regressions.

    Every exit path of main() lands here — success, hard-fail gate,
    deadline, and the backend-unreachable path that used to die as an
    unstructured log line — so the trajectory records when the hardware
    came and went, not just the runs that survived. Never raises."""
    from dllama_tpu.obsv import trajectory as _traj

    bench = (result or {}).get("metric") or "bench"
    gates = {"deadline": status != "timeout",
             "backend": status != "tpu_unreachable",
             "hard_fail": status == "ok"}
    rep = _traj.append_row(bench, status, result=result, gates=gates,
                           error=error)
    for flag in rep["regressions"]:
        log(f"trajectory REGRESSION vs last same-host {bench} run: {flag}")
    if rep["path"]:
        log(f"trajectory: {status} row appended to {rep['path']} "
            f"({len(rep['regressions'])} regression flag(s))")


def main() -> None:
    # metric name for the error path, resolvable without touching jax
    choice = os.environ.get("BENCH_MODEL", "")
    err_phase = ("prefill" if _prefill_count()
                 else "prefix" if _env_count("BENCH_PREFIX")
                 else "overlap" if _env_count("BENCH_OVERLAP")
                 else "reduce" if _env_count("BENCH_REDUCE")
                 else "serve" if _env_count("BENCH_CONTINUOUS")
                 else "faults" if _env_count("BENCH_FAULTS")
                 else "integrity" if _env_count("BENCH_INTEGRITY")
                 else "obs" if _env_count("BENCH_OBS")
                 else "router" if _env_count("BENCH_ROUTER")
                 else "disagg" if _env_count("BENCH_DISAGG")
                 else "failover" if _env_count("BENCH_FAILOVER")
                 else "workloads" if _env_count("BENCH_WORKLOADS")
                 else "elastic" if _env_count("BENCH_ELASTIC")
                 else "c10k" if _env_count("BENCH_C10K")
                 else "decode")
    err_metric = {"tiny": "tinyllama_1.1b", "llama3": "llama3_8b",
                  "moe": "mixtral_lite", "grok": "grok1_lite",
                  "smoke": "smoke"}.get(
        choice, "llama2_7b") + f"_{err_phase}_ms_per_token"

    # In-process deadline from PROCESS START (probes included): the probes
    # bound backend INIT, but a tunnel can wedge mid-run (observed: param
    # build hanging after a green probe). The timer emits the clean JSON
    # error record and hard-exits so neither the driver's bench run nor the
    # battery's outer `timeout` ever swallows the machine-readable failure.
    import threading

    deadline_s = float(os.environ.get("BENCH_DEADLINE_S", "1200"))

    def _deadline():
        err = (f"bench exceeded {deadline_s:.0f}s deadline "
               "(tunnel wedged mid-run?)")
        print(json.dumps({
            "metric": err_metric,
            "value": None,
            "unit": "ms/token",
            "vs_baseline": None,
            "error": err,
        }), flush=True)
        _trajectory_note("timeout", result={"metric": err_metric},
                         error=err)
        os._exit(1)

    if deadline_s > 0:
        timer = threading.Timer(deadline_s, _deadline)
        timer.daemon = True
        timer.start()

    nrouter = _env_count("BENCH_ROUTER")
    ndisagg = _env_count("BENCH_DISAGG")
    nfailover = _env_count("BENCH_FAILOVER")
    nworkloads = _env_count("BENCH_WORKLOADS")
    nelastic = _env_count("BENCH_ELASTIC")
    nc10k = _env_count("BENCH_C10K")
    if nrouter or ndisagg or nfailover or nworkloads or nelastic or nc10k:
        # the router, disaggregation, failover and workload replays are
        # jax-free IN THIS PROCESS (replicas are CPU subprocesses), so
        # branch before the backend probes: a dead TPU tunnel must not
        # block a pure-CPU fleet replay
        try:
            result = (run_router_bench(nrouter) if nrouter
                      else run_disagg_bench(ndisagg) if ndisagg
                      else run_failover_bench(nfailover) if nfailover
                      else run_workloads_bench(nworkloads) if nworkloads
                      else run_elastic_bench(nelastic) if nelastic
                      else run_c10k_bench(nc10k))
        except Exception as e:  # noqa: BLE001 — emit the machine-readable record
            result = {"metric": err_metric, "value": None,
                      "unit": ("req/s" if nrouter
                               else "conns" if nc10k else "ms"),
                      "vs_baseline": None,
                      "error": f"{type(e).__name__}: {e}"}
        if deadline_s > 0:
            timer.cancel()
        print(json.dumps(result), flush=True)
        _trajectory_note("error" if result.get("error") else "ok",
                         result=result, error=result.get("error"))
        raise SystemExit(1 if result.get("error") else 0)

    if os.environ.get("DLLAMA_PLATFORM"):
        # same escape hatch as the CLI: force the backend via jax.config
        # (works even when a sitecustomize pinned another platform)
        import jax

        jax.config.update("jax_platforms", os.environ["DLLAMA_PLATFORM"])
        quant_ok = ("BENCH_WEIGHTS" in os.environ
                    or _probe_q40_with_fallback()[0])
    else:
        # IMPORTANT: probe before anything initializes this process's
        # backend — a child spawned after the parent holds an exclusive TPU
        # would silently land on CPU and validate nothing. A successful
        # quant probe doubles as the backend-liveness check; a TIMED-OUT
        # one is the tunnel-down signature (kernel bugs fail fast with a
        # traceback), so only a fast failure pays the second probe that
        # tells "kernels unusable" apart from "backend dead". Either way a
        # dead backend exits cleanly instead of hanging in jax.devices().
        if "BENCH_WEIGHTS" in os.environ:
            probed, detail = False, ""
            alive, bdetail = _backend_alive()
        else:
            probed, detail = _probe_q40_with_fallback()
            if probed:
                alive, bdetail = True, ""
            elif "timed out" in detail:
                alive, bdetail = False, detail
            else:
                alive, bdetail = _backend_alive()
        if not alive:
            print(json.dumps({
                "metric": err_metric,
                "value": None,
                "unit": "ms/token",
                "vs_baseline": None,
                "error": f"backend unreachable: {bdetail}",
            }), flush=True)
            # the round the trajectory exists for: a structured
            # tpu_unreachable row instead of a vanished run
            _trajectory_note("tpu_unreachable",
                             result={"metric": err_metric},
                             error=f"backend unreachable: {bdetail}")
            raise SystemExit(1)
        quant_ok = probed or "BENCH_WEIGHTS" in os.environ
    if not quant_ok and "BENCH_WEIGHTS" not in os.environ:
        log("q40 kernel probe failed/timed out; bench will use bf16 weights")
    # after the quant probes (backend known reachable), before this process
    # inits the backend: a flash compile failure must downgrade, not crash
    _probe_flash_kernel()

    import jax

    platform = jax.devices()[0].platform
    choice = os.environ.get("BENCH_MODEL", "")
    if choice == "smoke" or (not choice and platform == "cpu"
                             and (_env_count("BENCH_CONTINUOUS")
                                  or _env_count("BENCH_FAULTS")
                                  or _env_count("BENCH_INTEGRITY")
                                  or _env_count("BENCH_OBS")
                                  or _env_count("BENCH_PREFIX")
                                  or _env_count("BENCH_OVERLAP")
                                  or _env_count("BENCH_REDUCE")
                                  or _prefill_count())):
        # the scheduling replays (continuous-vs-static, fault boundedness,
        # prefill stall) measure SCHEDULING, so the CPU default is a shape
        # small enough to replay inside CI budgets
        name, cfg_dict = "smoke", SMOKE_SERVE
    elif choice == "tiny" or (not choice and platform == "cpu"):
        name, cfg_dict = "tinyllama_1.1b", TINYLLAMA_1_1B
    elif choice == "llama3":
        # the north-star config (no published same-hardware baseline number;
        # vs_baseline stays null — the 7B default is the comparable metric)
        name, cfg_dict = "llama3_8b", LLAMA3_8B
    elif choice == "moe":
        name, cfg_dict = "mixtral_lite", MIXTRAL_LITE
    elif choice == "grok":
        name, cfg_dict = "grok1_lite", GROK1_LITE
    else:
        name, cfg_dict = "llama2_7b", LLAMA2_7B

    ms = weights = None
    fallback_reason = None
    try:
        ms, weights = run_decode_bench(cfg_dict, quant_ok=quant_ok)
    except Exception as e:  # noqa: BLE001 — OOM etc.: fall back to the small shape
        if name != "llama2_7b":
            raise
        fallback_reason = f"{type(e).__name__}: {e}"
        log(f"7B bench failed ({fallback_reason}); falling back to TinyLlama shape")
    if ms is None:
        # run the fallback OUTSIDE the except block: the live traceback would
        # pin the 7B device buffers and re-OOM the fallback
        import gc

        gc.collect()
        jax.clear_caches()
        name, cfg_dict = "tinyllama_1.1b", TINYLLAMA_1_1B
        ms, weights = run_decode_bench(cfg_dict, quant_ok=quant_ok)

    phase = ("prefill" if _prefill_count()
             else "prefix" if _env_count("BENCH_PREFIX")
             else "overlap" if _env_count("BENCH_OVERLAP")
             else "reduce" if _env_count("BENCH_REDUCE")
             else "serve" if _env_count("BENCH_CONTINUOUS")
             else "faults" if _env_count("BENCH_FAULTS")
             else "integrity" if _env_count("BENCH_INTEGRITY")
             else "obs" if _env_count("BENCH_OBS")
             else "decode")
    result = {
        "metric": f"{name}_{phase}_ms_per_token",
        "value": round(ms, 3),
        "unit": "ms/token",
        # only meaningful for the same model the baseline measured (7B);
        # a ratio against a 1.1B run would be apples-to-oranges; the prefill
        # mode compares legitimately (the reference prefills at decode cost)
        # but stays unclaimed here — the phase-tagged metric speaks for itself
        # ... and only at the stock context length (BENCH_SEQ changes the
        # per-token work, so the ratio would compare different jobs)
        "vs_baseline": (round(BASELINE_7B_SINGLE_NODE_MS / ms, 2)
                        if name == "llama2_7b" and phase == "decode"
                        and not _seq_override() else None),
        "baseline": "llama2-7b 1x GCP c3d-highcpu-30, 101.81 ms/token (reference README.md:88)",
        "weights": weights,
        "platform": jax.devices()[0].device_kind,
        "n_devices": len(jax.devices()),
    }
    if fallback_reason is not None:
        # a fallback number must never read as a green headline run
        result["error"] = f"7B CONFIG FAILED, fallback metric only: {fallback_reason}"
    if deadline_s > 0:
        # a run finishing near the deadline must not emit a second (error)
        # JSON record during teardown — the success line below is final
        timer.cancel()
    print(json.dumps(result), flush=True)
    _trajectory_note("error" if result.get("error") else "ok",
                     result=result, error=result.get("error"))


if __name__ == "__main__":
    main()
